"""Controller-side object registry replacing H2O's distributed K/V store.

Reference: water/DKV.java, water/Key.java (key→home-node hashing, Key.java:169),
water/Value.java (byte[]/POJO duality), water/Atomic.java (home-node CAS),
water/Lockable.java (read/write locks on Frames/Models).

TPU-native design: JAX is single-controller, so the *control plane* needs no
distribution at all — one registry maps keys to Python objects whose heavy
payloads (Vec data) are sharded jax.Arrays already resident in device HBM.
What survives from DKV's design:
  * keys as the universal handle between subsystems (frames, models, jobs);
  * write-locking of keyed objects while a job mutates them (Lockable);
  * atomic read-modify-write (Atomic) — here a plain lock, since there is
    exactly one writer process.
"""

from __future__ import annotations

import bisect
import hashlib
import io as _io
import threading
import time
from typing import Any

import numpy as np

from h2o3_tpu.analysis.lockdep import make_rlock
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.obs.timeline import span as _span

REHOMED_KEYS = _om.counter(
    "h2o3_dkv_rehome_keys_total",
    "DKV keys re-homed after a membership change (consistent-hash ring "
    "moved their home node)")
REHOMED_BYTES = _om.counter(
    "h2o3_dkv_rehome_bytes_total",
    "compact codec bytes shipped by DKV re-home migrations (packed "
    "data+mask planes via the tier pager, never device arrays)")


class HashRing:
    """Consistent-hash key→home-node map — the Key.java:169 home-node
    hash rebuilt so membership changes move a BOUNDED key set.

    The reference hashes `key % cloud_size`: adding or losing one node
    re-homes nearly every key. A ring of `vnodes` virtual points per node
    moves only the keys whose arc changed — on average 1/n of them for a
    single node join/leave."""

    def __init__(self, nodes, vnodes: int = 64):
        self.nodes = sorted(set(int(n) for n in nodes))
        self.vnodes = int(vnodes)
        points = []
        for n in self.nodes:
            for v in range(self.vnodes):
                points.append((self._hash(f"node:{n}:{v}"), n))
        points.sort()
        self._points = points
        self._keys = [p[0] for p in points]

    @staticmethod
    def _hash(s: str) -> int:
        return int.from_bytes(
            hashlib.md5(s.encode()).digest()[:8], "big")

    def node_for(self, key: str) -> int:
        if not self._points:
            return 0
        h = self._hash(key)
        i = bisect.bisect_right(self._keys, h)
        if i == len(self._points):
            i = 0
        return self._points[i][1]


def _plane_payload(data: np.ndarray, mask) -> bytes:
    """Serialize packed codec planes to the compact wire form a re-home
    move ships (npz of the dtype-packed data + optional u8 mask — the
    tier pager's host-tier representation, never decoded f32, never a
    device array)."""
    buf = _io.BytesIO()
    if mask is None:
        np.savez(buf, data=data)
    else:
        np.savez(buf, data=data, mask=mask)
    return buf.getvalue()


def _plane_restore(payload: bytes):
    with np.load(_io.BytesIO(payload)) as z:
        return z["data"], (z["mask"] if "mask" in z.files else None)


# replay-divergence sanitizer seam (analysis/divergence.py): when
# H2O3_DIVERGENCE is enabled this is its _record function and every
# replicated-state mutation reports (op, key, value) into the active
# request scope. None (the default) costs one global load per mutation.
_div_hook = None


class _DKV:
    def __init__(self):
        self._store: dict[str, Any] = {}
        self._locks: dict[str, str] = {}  # key -> job/owner name holding write lock
        # lockdep class "dkv": the registry mutex nests inside nearly
        # every subsystem, so it is the lock the order graph must see
        self._mutex = make_rlock("dkv")
        self._counter = 0
        # ---- elastic membership (deploy/membership) ---------------------
        # consistent-hash home-node map + background re-home state. On a
        # single-host cloud everything homes on node 0 and none of this
        # moves; a membership epoch bump re-homes only the keys whose
        # ring arc changed, shipping compact codec bytes in a background
        # worker with read-through (the OLD home keeps serving until the
        # key's planes landed).
        self._ring = HashRing([0])
        self._homes: dict[str, int] = {}
        self._migrating: set = set()
        self._rehome_epoch = 1
        self._rehome_keys_moved = 0
        self._rehome_bytes_moved = 0
        self._rehome_thread = None
        self._rehome_queue: list = []
        self._rehome_hook = None    # test seam: called per migrated key

    # ---- basic ops (DKV.put/get/remove) ---------------------------------
    def put(self, key: str, value: Any) -> str:
        with self._mutex:
            old = self._store.get(key)
            self._store[key] = value
            # preserve an existing home: overwriting a key mid-migration
            # must not flip home_of to the new ring assignment before the
            # planes landed (the read-through contract) — only NEW keys
            # take the ring's current answer
            if key not in self._homes:
                self._homes[key] = self._ring.node_for(key)
        # a retrain overwriting a model key frees the OLD generation's
        # serving residency on every tier exactly once; outside the
        # mutex like _on_remove, so cache/pager locks never nest under
        # `dkv`
        if old is not None and old is not value \
                and hasattr(old, "_on_replace"):
            old._on_replace()
        hk = _div_hook
        if hk is not None:
            hk("put", key, value)
        return key

    def get(self, key: str, default=None):
        with self._mutex:
            v = self._store.get(key, default)
        # chunk-tiered objects (Frames) re-promote transparently on get
        # (water/Value.java mem/disk duality, now chunk-granular): the
        # hook runs OUTSIDE the registry mutex so pager I/O never nests
        # under `dkv`
        hook = getattr(v, "_tier_on_get", None)
        if hook is not None:
            hook()
        return v

    def raw_get(self, key: str, default=None):
        """Registry hit WITHOUT tier promotion — for the memory manager's
        accounting/cleaning and metric scrapes, which must not fault
        demoted chunks back in."""
        with self._mutex:
            return self._store.get(key, default)

    def __contains__(self, key: str) -> bool:
        with self._mutex:
            return key in self._store

    def remove(self, key: str):
        with self._mutex:
            v = self._store.pop(key, None)
            self._locks.pop(key, None)
            self._homes.pop(key, None)
            self._migrating.discard(key)
        if v is not None and hasattr(v, "_on_remove"):
            v._on_remove()
        hk = _div_hook
        if hk is not None:
            hk("remove", key, None)

    def keys(self) -> list[str]:
        with self._mutex:
            return sorted(self._store.keys())

    def clear(self):
        with self._mutex:
            self._store.clear()
            self._locks.clear()
            self._homes.clear()
            self._migrating.clear()
            self._rehome_queue.clear()

    # ---- atomic update (water/Atomic.java:10) ---------------------------
    def atomic(self, key: str, fn):
        """Atomically apply fn(old_value) -> new_value under the registry lock."""
        with self._mutex:
            nv = fn(self._store.get(key))
            if nv is None:
                self._store.pop(key, None)
            else:
                self._store[key] = nv
        hk = _div_hook
        if hk is not None:
            hk("atomic", key, nv)
        return nv

    # ---- write locks (water/Lockable.java) ------------------------------
    def write_lock(self, key: str, owner: str):
        with self._mutex:
            holder = self._locks.get(key)
            if holder is not None and holder != owner:
                raise RuntimeError(
                    f"key {key!r} is write-locked by {holder!r}")
            self._locks[key] = owner

    def unlock(self, key: str, owner: str):
        with self._mutex:
            if self._locks.get(key) == owner:
                del self._locks[key]

    def is_locked(self, key: str) -> bool:
        with self._mutex:
            return key in self._locks

    # ---- census (obs/metrics gauges + /3/WaterMeter) --------------------
    def stats(self) -> dict:
        """Registry census: live keys, frames and their host-side bytes.
        Uses raw_get so scraping /metrics never faults spilled frames
        back into memory."""
        with self._mutex:
            keys = list(self._store.keys())
            locked = len(self._locks)
        from h2o3_tpu.core.frame import Frame
        from h2o3_tpu.core.memory import MANAGER
        nframes = 0
        fbytes = 0
        for k in keys:
            v = self.raw_get(k)
            if isinstance(v, Frame):
                nframes += 1
                try:
                    fbytes += MANAGER.frame_bytes(v)
                except Exception:   # noqa: BLE001 — census must never raise
                    pass
        return {"keys": len(keys), "frames": nframes,
                "frame_bytes": fbytes, "write_locked": locked}

    # ---- elastic membership: homes + background re-home -----------------
    def home_of(self, key: str) -> int:
        """The node currently SERVING this key. During a migration the
        old home keeps answering (read-through) — home_of flips to the
        ring's new assignment only once the key's planes landed."""
        with self._mutex:
            if key in self._homes:
                return self._homes[key]
            return self._ring.node_for(key)

    def ring_nodes(self) -> list:
        with self._mutex:
            return list(self._ring.nodes)

    def set_membership(self, nodes, epoch: int = None):
        """Rebuild the consistent-hash ring for a new membership epoch
        and queue the BOUNDED set of keys whose home moved for
        background re-home. Returns the list of keys that will move.
        Called by the deploy/membership listener on every epoch bump."""
        ring = HashRing(nodes)
        with self._mutex:
            if epoch is not None:
                self._rehome_epoch = epoch
            self._ring = ring
            moved = [k for k, home in self._homes.items()
                     if ring.node_for(k) != home
                     and k not in self._migrating]
            self._migrating.update(moved)
            self._rehome_queue.extend(moved)
            if moved:
                self._ensure_rehome_worker_locked()
        return moved

    def _ensure_rehome_worker_locked(self):
        # a live _rehome_thread is still inside its drain loop and will
        # observe the keys just queued (retirement happens under this
        # mutex); None means retired or never started — spawn
        if self._rehome_thread is not None:
            return
        t = threading.Thread(target=self._rehome_loop, daemon=True,
                             name="h2o3-dkv-rehome")
        self._rehome_thread = t   # h2o3-ok: R003 _locked helper — every caller holds self._mutex (retirement in _rehome_loop is mutex-held too)
        t.start()

    def _rehome_loop(self):
        """Background DKV re-home: drain the moved-key queue, shipping
        each key's compact codec-byte planes to its new home. Read
        serving is untouched while this runs — DKV.get answers from the
        registry and home_of() keeps naming the old home until the
        per-key swap below."""
        while True:
            with self._mutex:
                if not self._rehome_queue:
                    # retire UNDER the mutex: set_membership's spawn
                    # check is serialized against this, so an enqueue
                    # either lands before this check (we keep draining)
                    # or sees _rehome_thread cleared and spawns a fresh
                    # worker — queued keys can never strand
                    self._rehome_thread = None
                    return
                key = self._rehome_queue.pop(0)
            try:
                self._migrate_key(key)
            except Exception as ex:   # noqa: BLE001 — a failed move must
                from h2o3_tpu.utils import log as _ulog  # not kill the loop
                _ulog.err("dkv re-home of %r failed: %r", key, ex)
                with self._mutex:
                    self._migrating.discard(key)

    def _migrate_key(self, key: str):
        """Move one key to its ring home: pack each chunk's codec-byte
        planes (the tier pager's host-tier form — compact bytes, not
        device arrays), round-trip them through the wire encoding, verify
        bit-exactness per plane, install the shipped copies, then flip
        home_of. Values without packed chunks (models, jobs) move as
        zero-byte control records."""
        v = self.raw_get(key)
        if v is None:                     # removed while queued
            with self._mutex:
                self._migrating.discard(key)
            return
        hook = self._rehome_hook
        if hook is not None:
            hook(key)                     # test seam: pause mid-migration
        moved_bytes = 0
        with _span("membership.rehome", key=key):
            for ch in self._value_chunks(v):
                if not self._chunk_shippable(ch):
                    # multi-controller SPMD shard: the planes live
                    # partitioned across the device runtime, not on one
                    # node — the move is control-plane only (home flips,
                    # no payload; the replay channel keeps every process
                    # holding its own shards)
                    continue
                data, mask = ch.staging_view()
                payload = _plane_payload(data, mask)
                rdata, rmask = _plane_restore(payload)
                if rdata.tobytes() != data.tobytes() or (
                        (mask is None) != (rmask is None)) or (
                        mask is not None
                        and rmask.tobytes() != mask.tobytes()):
                    raise RuntimeError(
                        f"re-home payload of {key!r} not bit-exact")
                moved_bytes += len(payload)
                # install the SHIPPED copy as the chunk's host planes —
                # the new home serves exactly the bytes that moved
                with ch._io:
                    if ch._host is not None:
                        ch._host = (rdata,
                                    None if rmask is None else rmask)
        with self._mutex:
            self._homes[key] = self._ring.node_for(key)
            self._migrating.discard(key)
            self._rehome_keys_moved += 1
            self._rehome_bytes_moved += moved_bytes
        REHOMED_KEYS.inc()
        if moved_bytes:
            REHOMED_BYTES.inc(moved_bytes)

    @staticmethod
    def _chunk_shippable(ch) -> bool:
        """A chunk's planes can be packaged from THIS process: host codec
        bytes exist, or the device arrays are fully addressable. SPMD
        global shards (multi-controller clouds) are not — device_get
        from one process would raise."""
        dev = ch._dev
        if dev is None or ch._host is not None:
            return True
        return bool(getattr(dev[0], "is_fully_addressable", True))

    @staticmethod
    def _value_chunks(v):
        """The tier chunks backing a DKV value (a Frame's Vec planes);
        empty for plain control objects."""
        out = []
        for vec in getattr(v, "vecs", []) or []:
            ch = getattr(vec, "_chunk", None)
            if ch is not None:
                out.append(ch)
            codes = getattr(vec, "_codes_chunk", None)
            if codes is not None:   # StrVec dictionary code plane
                out.append(codes)
            for attr in ("_nzr_chunk", "_nzv_chunk", "_uuid_chunk"):
                nz = getattr(vec, attr, None)
                if nz is not None:  # SparseVec nz planes / UuidVec lanes
                    out.append(nz)
        return out

    def rehome_status(self) -> dict:
        """GET /3/Cloud's re-home view (and the test harness's barrier)."""
        with self._mutex:
            return {"epoch": self._rehome_epoch,
                    "pending": len(self._migrating),
                    "keys_moved": self._rehome_keys_moved,
                    "bytes_moved": self._rehome_bytes_moved,
                    "nodes": list(self._ring.nodes)}

    # ---- key minting (water/Key.make) -----------------------------------
    def make_key(self, prefix: str = "obj") -> str:
        # deterministic: broadcast replay re-mints keys on EVERY host,
        # and the serialized replay stream bumps the counter in the same
        # order everywhere — a wall-clock component here forked the key
        # namespace across the cloud (the R019 divergence class)
        with self._mutex:
            self._counter += 1
            return f"{prefix}_{self._counter:04d}"


DKV = _DKV()

# module-level registration reading the module global (the microbatch
# pattern: survives a test harness swapping DKV out)
_om.gauge("h2o3_dkv_rehome_pending",
          "DKV keys queued or mid-flight in the background re-home "
          "worker (reads serve through the old home until this drains)",
          fn=lambda: float(len(DKV._migrating)))
