"""Controller-side object registry replacing H2O's distributed K/V store.

Reference: water/DKV.java, water/Key.java (key→home-node hashing, Key.java:169),
water/Value.java (byte[]/POJO duality), water/Atomic.java (home-node CAS),
water/Lockable.java (read/write locks on Frames/Models).

TPU-native design: JAX is single-controller, so the *control plane* needs no
distribution at all — one registry maps keys to Python objects whose heavy
payloads (Vec data) are sharded jax.Arrays already resident in device HBM.
What survives from DKV's design:
  * keys as the universal handle between subsystems (frames, models, jobs);
  * write-locking of keyed objects while a job mutates them (Lockable);
  * atomic read-modify-write (Atomic) — here a plain lock, since there is
    exactly one writer process.
"""

from __future__ import annotations

import time
from typing import Any

from h2o3_tpu.analysis.lockdep import make_rlock


class _DKV:
    def __init__(self):
        self._store: dict[str, Any] = {}
        self._locks: dict[str, str] = {}  # key -> job/owner name holding write lock
        # lockdep class "dkv": the registry mutex nests inside nearly
        # every subsystem, so it is the lock the order graph must see
        self._mutex = make_rlock("dkv")
        self._counter = 0

    # ---- basic ops (DKV.put/get/remove) ---------------------------------
    def put(self, key: str, value: Any) -> str:
        with self._mutex:
            self._store[key] = value
        return key

    def get(self, key: str, default=None):
        with self._mutex:
            v = self._store.get(key, default)
        # chunk-tiered objects (Frames) re-promote transparently on get
        # (water/Value.java mem/disk duality, now chunk-granular): the
        # hook runs OUTSIDE the registry mutex so pager I/O never nests
        # under `dkv`
        hook = getattr(v, "_tier_on_get", None)
        if hook is not None:
            hook()
        return v

    def raw_get(self, key: str, default=None):
        """Registry hit WITHOUT tier promotion — for the memory manager's
        accounting/cleaning and metric scrapes, which must not fault
        demoted chunks back in."""
        with self._mutex:
            return self._store.get(key, default)

    def __contains__(self, key: str) -> bool:
        with self._mutex:
            return key in self._store

    def remove(self, key: str):
        with self._mutex:
            v = self._store.pop(key, None)
            self._locks.pop(key, None)
        if v is not None and hasattr(v, "_on_remove"):
            v._on_remove()

    def keys(self) -> list[str]:
        with self._mutex:
            return sorted(self._store.keys())

    def clear(self):
        with self._mutex:
            self._store.clear()
            self._locks.clear()

    # ---- atomic update (water/Atomic.java:10) ---------------------------
    def atomic(self, key: str, fn):
        """Atomically apply fn(old_value) -> new_value under the registry lock."""
        with self._mutex:
            nv = fn(self._store.get(key))
            if nv is None:
                self._store.pop(key, None)
            else:
                self._store[key] = nv
            return nv

    # ---- write locks (water/Lockable.java) ------------------------------
    def write_lock(self, key: str, owner: str):
        with self._mutex:
            holder = self._locks.get(key)
            if holder is not None and holder != owner:
                raise RuntimeError(
                    f"key {key!r} is write-locked by {holder!r}")
            self._locks[key] = owner

    def unlock(self, key: str, owner: str):
        with self._mutex:
            if self._locks.get(key) == owner:
                del self._locks[key]

    def is_locked(self, key: str) -> bool:
        with self._mutex:
            return key in self._locks

    # ---- census (obs/metrics gauges + /3/WaterMeter) --------------------
    def stats(self) -> dict:
        """Registry census: live keys, frames and their host-side bytes.
        Uses raw_get so scraping /metrics never faults spilled frames
        back into memory."""
        with self._mutex:
            keys = list(self._store.keys())
            locked = len(self._locks)
        from h2o3_tpu.core.frame import Frame
        from h2o3_tpu.core.memory import MANAGER
        nframes = 0
        fbytes = 0
        for k in keys:
            v = self.raw_get(k)
            if isinstance(v, Frame):
                nframes += 1
                try:
                    fbytes += MANAGER.frame_bytes(v)
                except Exception:   # noqa: BLE001 — census must never raise
                    pass
        return {"keys": len(keys), "frames": nframes,
                "frame_bytes": fbytes, "write_locked": locked}

    # ---- key minting (water/Key.make) -----------------------------------
    def make_key(self, prefix: str = "obj") -> str:
        with self._mutex:
            self._counter += 1
            return f"{prefix}_{self._counter:04d}_{int(time.time()) % 100000}"


DKV = _DKV()
