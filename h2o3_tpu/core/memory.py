"""Memory manager — water/MemoryManager.java + water/Cleaner.java rebuilt.

Reference: MemoryManager (allocation accounting, OOM callbacks),
Cleaner.java:11 (a background "user-mode swap": LRU-ages cached Values and
spills cold ones to ice_root disk, reloading transparently on access),
FrameSizeMonitor.java.

TPU-native design: the scarce resource is device HBM. Paging itself is
CHUNK-granular and lives in core/tiering.py (HBM → host codec bytes →
disk); this module is the frame-level facade the rest of the runtime
talks to — byte accounting for the DKV census, Cleaner wakeups at frame
registration, explicit whole-frame spill/load, and pinning. Frame-granular
`_Spilled` placeholders are gone: a "spilled" frame is simply one whose
every chunk sits on the disk tier, and `DKV.get` promotes its codec bytes
back to host RAM while HBM faults stay lazy per chunk (so a frame
slightly over budget pages a few chunks instead of ping-ponging whole)."""

from __future__ import annotations

from h2o3_tpu.core import tiering as _tiering
from h2o3_tpu.io import spill as _spill


def _frame_chunks(frame):
    out = []
    for v in frame.vecs:
        # StrVec code planes, SparseVec nz planes and UuidVec word lanes
        # are all pageable chunks alongside the dense packed plane
        for attr in ("_chunk", "_codes_chunk", "_nzr_chunk",
                     "_nzv_chunk", "_uuid_chunk"):
            c = getattr(v, attr, None)
            if c is not None:
                out.append(c)
    return out


class MemoryManager:
    def __init__(self):
        self.pager = _tiering.PAGER

    # ---- config ---------------------------------------------------------
    @property
    def budget(self) -> int:
        """HBM budget in bytes (0 = unlimited) — the pager's ladder top."""
        return self.pager.hbm_budget

    @budget.setter
    def budget(self, value: int):
        self.pager.hbm_budget = int(value)

    @property
    def ice_root(self) -> str:
        return _spill.get_ice_root()

    @ice_root.setter
    def ice_root(self, path: str):
        _spill.set_ice_root(path)

    # ---- accounting (MemoryManager.java) --------------------------------
    def frame_bytes(self, frame) -> int:
        """MEMORY-resident packed bytes of the frame's pageable planes
        (HBM or host RAM) — the DKV census number. Chunks whose only
        copy is a spill file contribute 0, matching the old contract
        where spilled frames dropped out of the census. Str code planes,
        sparse nz planes and uuid word lanes all count: every column
        layout is pageable now."""
        return sum(c.nbytes for c in _frame_chunks(frame)
                   if c.tier != _tiering.TIER_DISK)

    def total_bytes(self) -> int:
        """HBM-resident packed chunk bytes, cluster-wide working set."""
        return self.pager.tier_bytes()[_tiering.TIER_HBM]

    def _chunks_of(self, key: str):
        from h2o3_tpu.core.frame import Frame
        from h2o3_tpu.core.kvstore import DKV
        # raw_get: accounting/cleaning must never fault chunks back in
        f = DKV.raw_get(key)
        return _frame_chunks(f) if isinstance(f, Frame) else []

    def touch(self, key: str):
        self.pager.touch_chunks(self._chunks_of(key))

    def pin(self, key: str):
        for c in self._chunks_of(key):
            c.pinned += 1

    def unpin(self, key: str):
        for c in self._chunks_of(key):
            if c.pinned:
                c.pinned -= 1

    # ---- the Cleaner (Cleaner.java:11) ----------------------------------
    def maybe_clean(self):
        """Cleaner wakeup: enforce the tier budgets, LRU-demoting cold
        chunks (no-op when no budget is set)."""
        return self.pager.maybe_demote()

    def spill(self, key: str, frame=None):
        """Demote every chunk of the frame to the disk tier (the explicit
        Cleaner spill; files land under ice_root via io/spill)."""
        from h2o3_tpu.core.kvstore import DKV
        frame = frame if frame is not None else DKV.raw_get(key)
        for c in _frame_chunks(frame):
            self.pager.demote(c, _tiering.TIER_DISK)
        return _spill.chunk_dir()

    def load(self, key: str):
        """Fault every chunk of the frame back to HBM (bulk reload)."""
        from h2o3_tpu.core.kvstore import DKV
        f = DKV.raw_get(key)
        if f is not None:
            for c in _frame_chunks(f):
                c.device()
        return f

    def is_spilled(self, key: str) -> bool:
        """True when the frame's every pageable chunk sits on disk."""
        chunks = self._chunks_of(key)
        return bool(chunks) and all(
            c.tier == _tiering.TIER_DISK for c in chunks)

    def is_hbm_resident(self, key: str) -> bool:
        """True when at least one of the frame's chunks is in HBM."""
        return any(c.tier == _tiering.TIER_HBM
                   for c in self._chunks_of(key))

    def stats(self) -> dict:
        from h2o3_tpu.core.frame import Frame
        from h2o3_tpu.core.kvstore import DKV
        spilled = [k for k in DKV.keys()
                   if isinstance(DKV.raw_get(k), Frame)
                   and self.is_spilled(k)]
        st = self.pager.stats()
        return {"ice_root": self.ice_root, "budget_bytes": self.budget,
                "resident_bytes": st["tier_bytes"][_tiering.TIER_HBM],
                "tier_bytes": st["tier_bytes"],
                "faults": st["faults"], "spilled": sorted(spilled)}


MANAGER = MemoryManager()
