"""Memory manager — water/MemoryManager.java + water/Cleaner.java rebuilt.

Reference: MemoryManager (allocation accounting, OOM callbacks),
Cleaner.java:11 (a background "user-mode swap": LRU-ages cached Values and
spills cold ones to ice_root disk, reloading transparently on access),
FrameSizeMonitor.java.

TPU-native design: the scarce resource is device HBM, not JVM heap. The
manager accounts the HBM bytes of every registered Frame, and when a
configurable budget is exceeded, LRU-spills whole cold frames to the ice
directory (.hex snapshots via io/persist) and frees their device buffers.
Access through `DKV.get` transparently reloads (Value.java's mem/disk
duality, frame-granular instead of chunk-granular — device_put of a whole
column set is one bulk host→HBM transfer, which is how TPUs like it).
There is no background thread: `maybe_clean()` runs at registration points
(frame creation), the moral equivalent of Cleaner wakeups."""

from __future__ import annotations

import os
import time

import numpy as np

DEFAULT_BUDGET = int(os.environ.get("H2O3_TPU_HBM_BUDGET_MB", "0")) * 2**20


class MemoryManager:
    def __init__(self, ice_root: str | None = None,
                 budget_bytes: int = DEFAULT_BUDGET):
        self.ice_root = ice_root or os.path.join(
            os.path.expanduser("~"), ".h2o3_tpu_ice")
        self.budget = budget_bytes          # 0 = unlimited (no spilling)
        self._touch: dict[str, float] = {}  # frame key -> last access
        self._spilled: dict[str, str] = {}  # frame key -> snapshot path
        self._pinned: set[str] = set()

    # ---- accounting (MemoryManager.java) --------------------------------
    def frame_bytes(self, frame) -> int:
        total = 0
        for v in frame.vecs:
            for arr in (getattr(v, "data", None), getattr(v, "mask", None)):
                if arr is not None:
                    total += int(np.prod(arr.shape)) * arr.dtype.itemsize
        return total

    def total_bytes(self) -> int:
        # raw_get: accounting must never fault spilled frames back into HBM
        from h2o3_tpu.core.frame import Frame
        from h2o3_tpu.core.kvstore import DKV
        return sum(self.frame_bytes(o) for k in DKV.keys()
                   if k not in self._spilled
                   and isinstance(o := DKV.raw_get(k), Frame))

    def touch(self, key: str):
        self._touch[key] = time.time()

    def pin(self, key: str):
        self._pinned.add(key)

    def unpin(self, key: str):
        self._pinned.discard(key)

    # ---- the Cleaner (Cleaner.java:11) ----------------------------------
    def maybe_clean(self):
        """Spill LRU frames until under budget (no-op when budget==0)."""
        if not self.budget:
            return []
        from h2o3_tpu.core.frame import Frame
        from h2o3_tpu.core.kvstore import DKV
        live = [(k, DKV.raw_get(k)) for k in DKV.keys()
                if k not in self._spilled]
        frames = [(k, o) for k, o in live
                  if isinstance(o, Frame) and k not in self._pinned]
        used = sum(self.frame_bytes(o) for _, o in frames)
        if used <= self.budget:
            return []
        frames.sort(key=lambda kv: self._touch.get(kv[0], 0.0))
        spilled = []
        for k, f in frames:
            if used <= self.budget:
                break
            used -= self.frame_bytes(f)
            self.spill(k, f)
            spilled.append(k)
        return spilled

    def spill(self, key: str, frame=None):
        """Write the frame to ice and drop its device buffers."""
        from h2o3_tpu.core.kvstore import DKV
        from h2o3_tpu.io.persist import export_frame
        frame = frame if frame is not None else DKV.get(key)
        os.makedirs(self.ice_root, exist_ok=True)
        path = os.path.join(self.ice_root, f"{key}.hex")
        export_frame(frame, path)
        self._spilled[key] = path
        DKV.atomic(key, lambda _old: _Spilled(key, path))
        return path

    def load(self, key: str):
        """Reload a spilled frame into HBM (Value.loadPersist analog)."""
        from h2o3_tpu.core.kvstore import DKV
        from h2o3_tpu.io.persist import import_frame
        path = self._spilled.pop(key, None)
        if path is None:
            # concurrent loader won the race — wait for its DKV.put to land
            for _ in range(2000):
                v = DKV.raw_get(key)
                if not getattr(v, "spilled", False):
                    return v
                time.sleep(0.005)
            raise TimeoutError(f"spilled frame {key!r} never reloaded")
        f = import_frame(path, key=key)
        DKV.put(key, f)
        self.touch(key)
        try:
            os.remove(path)
        except OSError:
            pass
        return f

    def is_spilled(self, key: str) -> bool:
        return key in self._spilled

    def stats(self) -> dict:
        return {"ice_root": self.ice_root, "budget_bytes": self.budget,
                "resident_bytes": self.total_bytes(),
                "spilled": sorted(self._spilled)}


class _Spilled:
    """Registry placeholder for a spilled frame; DKV.get resolves it."""

    def __init__(self, key, path):
        self.key = key
        self.path = path
        self.spilled = True


MANAGER = MemoryManager()


def resolve(obj):
    """Transparent reload when a registry hit is a spill placeholder."""
    if isinstance(obj, _Spilled):
        return MANAGER.load(obj.key)
    return obj
