"""Job system: async work units with progress/cancel/exception propagation.

Reference: water/Job.java:23 — keyed job objects polled via REST /3/Jobs
(water/api/JobsHandler.java); exceptions from the distributed F/J tree
propagate into the job (water/MRThrow semantics).

TPU-native design: jobs run on controller threads (model builds are
controller-orchestrated loops launching jitted device programs); progress is a
plain float the work loop updates; cancellation is a cooperative flag checked
between device steps — the same contract Job.stop_requested() gives MRTasks.
"""

from __future__ import annotations

import contextlib
import threading
import time
import traceback
from typing import Callable, Optional

from h2o3_tpu.core.kvstore import DKV

RUNNING = "RUNNING"
DONE = "DONE"
FAILED = "FAILED"
CANCELLED = "CANCELLED"
CREATED = "CREATED"


class JobCancelled(Exception):
    pass


class Job:
    """An async job keyed in the registry (water/Job.java:23)."""

    def __init__(self, description: str = "", dest: Optional[str] = None):
        self.key = DKV.make_key("job")
        self.description = description
        self.dest = dest              # key of the object being built
        self.status = CREATED
        self.progress = 0.0
        self.progress_msg = ""
        # max_runtime_secs: absolute deadline; builders poll
        # `budget_exhausted` at their update() cadence and stop gracefully,
        # keeping the partial model (SharedTree stop_requested semantics)
        self.deadline: Optional[float] = None
        self.budget_exhausted = False
        # per-phase wall time (ms), accumulated by `with job.phase(...)`
        # blocks in the builders; surfaced in to_dict → /3/Jobs
        self.phases: dict[str, float] = {}
        self.exception: Optional[BaseException] = None
        self.traceback: Optional[str] = None
        self.start_time = 0.0
        self.end_time = 0.0
        self._stop_requested = threading.Event()
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        DKV.put(self.key, self)

    # ---- lifecycle ------------------------------------------------------
    def start(self, work: Callable[["Job"], object], background: bool = True) -> "Job":
        """Run `work(job)`; its return value is DKV-put under self.dest.

        Multi-tenant QoS: starting a job charges the launching request's
        principal against its concurrent-job quota (H2O3_QOS_MAX_JOBS →
        QuotaExceeded → REST 429) BEFORE the job transitions to RUNNING,
        and the worker thread re-enters that principal so the job's own
        device dispatches ride the batch lane (and nested jobs it spawns
        are not double-counted)."""
        from h2o3_tpu.obs import tracing as _tracing
        from h2o3_tpu.serving import qos as _qos
        # a REST job-route request pre-paid its quota charge BEFORE the
        # replay broadcast (see qos.prepay_job_slot); adopt it — only
        # job starts outside that flow charge here
        qos_slot = _qos.adopt_prepaid_job_slot()
        if qos_slot is None:
            qos_slot = _qos.acquire_job_slot()
        parent_principal = _tracing.principal()
        self.status = RUNNING
        # h2o3-ok: R016 wall-clock progress stamp for /3/Jobs display; no control flow or DKV key derivation reads it, so per-host divergence is cosmetic
        self.start_time = time.time()
        # jobs inherit the starting thread's trace (the REST request that
        # launched the build), so job.run/job.<phase> spans stitch into
        # GET /3/Trace/{id} even though the work runs on its own thread
        parent_trace = _tracing.current()

        def _run():
            from h2o3_tpu.obs import tracing as _tr
            from h2o3_tpu.obs.timeline import span
            try:
                with _tr.trace(parent_trace), \
                        _qos.job_context(parent_principal), \
                        span("job.run", job=self.key,
                             description=self.description) as _sp:
                    try:
                        result = work(self)
                    except JobCancelled:
                        raise
                    except BaseException as e:
                        # tag the span before it closes: the `error` attr
                        # is what the flight recorder's tail sampler keys
                        # on — without it a fast-failing traced job loses
                        # the downsample lottery
                        _sp.attrs["error"] = repr(e)
                        raise
                if result is not None and self.dest:
                    DKV.put(self.dest, result)
                self.progress = 1.0
                self.status = DONE
            except JobCancelled:
                self.status = CANCELLED
            except BaseException as e:  # propagate like MRThrow
                self.exception = e
                self.traceback = traceback.format_exc()
                self.status = FAILED
            finally:
                _qos.release_job_slot(qos_slot)
                # h2o3-ok: R016 wall-clock progress stamp (see start_time): display-only, never replicated into decisions
                self.end_time = time.time()
                self._done.set()

        if background:
            try:
                self._thread = threading.Thread(target=_run, daemon=True,
                                                name=f"job-{self.key}")
                self._thread.start()
            except BaseException as e:
                # Thread.start() can fail under thread exhaustion — the
                # worker that would have released the slot in its finally
                # never runs, so the quota charge would leak until process
                # death (R022 class: ISSUE-17's admission double-count)
                self.exception = e
                self.status = FAILED
                _qos.release_job_slot(qos_slot)
                self._done.set()
                raise
        else:
            _run()
        return self

    def join(self, timeout: Optional[float] = None):
        """Block until done; re-raise the job's exception (Job.get())."""
        self._done.wait(timeout)
        if self.exception is not None:
            raise self.exception
        if self.dest:
            return DKV.get(self.dest)
        return None

    # ---- phase timing (obs/timeline spans + /3/Jobs phases) -------------
    @contextlib.contextmanager
    def phase(self, name: str):
        """Time one builder phase: wall time accumulates under `name` in
        to_dict()["phases"], and the block is a span on /3/Timeline."""
        from h2o3_tpu.obs.timeline import span
        t0 = time.time()
        try:
            # h2o3-ok: R011 phase names are builder-supplied data (init/train/score), bounded by the algo's phase() calls
            with span(f"job.{name}", job=self.key):
                yield
        finally:
            dt = 1000.0 * (time.time() - t0)
            self.phases[name] = self.phases.get(name, 0.0) + dt

    # ---- progress & cancellation ---------------------------------------
    def update(self, progress: float, msg: str = ""):
        self.progress = float(progress)
        if msg:
            self.progress_msg = msg
        if self.deadline is not None and time.time() > self.deadline:
            self.budget_exhausted = True
        if self._stop_requested.is_set():
            raise JobCancelled()

    def stop(self):
        """Request cooperative cancellation (Job.stop())."""
        self._stop_requested.set()

    @property
    def stop_requested(self) -> bool:
        return self._stop_requested.is_set()

    @property
    def is_done(self) -> bool:
        return self._done.is_set()

    @property
    def run_time_ms(self) -> int:
        end = self.end_time or time.time()
        return int(1000 * (end - self.start_time)) if self.start_time else 0

    def to_dict(self) -> dict:
        """REST /3/Jobs schema."""
        return {
            "key": self.key, "description": self.description,
            "status": self.status, "progress": self.progress,
            "progress_msg": self.progress_msg, "dest": self.dest,
            "msec": self.run_time_ms,
            # snapshot first: the builder thread inserts phase keys while
            # /3/Jobs serializes concurrently
            "phases": {k: round(v, 3)
                       for k, v in list(self.phases.items())},
            "exception": repr(self.exception) if self.exception else None,
            "stacktrace": self.traceback,
        }


def jobs_list() -> list[dict]:
    return [DKV.get(k).to_dict() for k in DKV.keys() if k.startswith("job_")]
