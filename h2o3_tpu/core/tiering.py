"""DKV memory tiering — the Cleaner rebuilt as a chunk-granular pager.

Reference: water/Cleaner.java:11 (background "user-mode swap": LRU-ages
cached Values, spills cold ones to ice, reloads transparently on access),
water/MemoryManager.java (byte accounting), water/Value.java (mem/disk
duality — a Value's bytes can live in memory, on disk, or both).

TPU-native design: the unit of paging is one CHUNK — a Vec's packed data
plane plus its optional uint8 NA mask, the bulk `device_put` transfer
shape TPUs like. Three tiers:

  * HBM   — the decoded working set: packed `jax.Array` planes consumers
            read through `Vec.data`/`Vec.mask` (decode to f32 still fuses
            into consumer jits, exactly as before);
  * host  — the compressed codec bytes the parser already produced
            (dtype-packed numpy + mask), retained at ingest when tiering
            is active so an HBM demotion FREES device buffers without a
            device→host fetch;
  * disk  — per-chunk spill files under ice_root (io/spill.py), the
            PersistIce analog.

Demotion is LRU, driven by the packed-byte accounting of every live
chunk checked against `H2O3_TPU_HBM_BUDGET_MB` (and, off-CPU, the
device-memory gauges obs/metrics.py already exports — a budget breach in
`bytes_in_use` also triggers the ladder). Promotion is transparent:
faulting a chunk decodes nothing on host — it `device_put`s the packed
planes in one bulk transfer and lets XLA fuse the decode. A prefetch
worker overlaps the NEXT chunk's tier-up with the CURRENT chunk's
compute (parallel/mrtask.py `map_chunked` lookahead).

Locks (lockdep classes, ordered): `tiering.io` (per-chunk transfer
serialization, one class for every instance) is acquired FIRST, then
`tiering.residency` (the pager's maps + accounting). Neither is ever
held while taking `dkv` — frame→chunk resolution happens before pager
entry — so the pager nests cleanly under every DKV caller.

Metrics: `h2o3_dkv_tier_bytes{tier}` (occupancy),
`h2o3_dkv_tier_faults_total{tier}` (promotions, labeled by the tier
faulted FROM), `h2o3_dkv_tier_evictions_total{tier}` (demotions, labeled
by the tier evicted TO). Fault/evict events are also recorded on the
caller's open timeline span (`Span.event`), so a traced MRTask shows
exactly which chunks paged inside it.
"""

from __future__ import annotations

import itertools
import queue
import threading
import weakref
from collections import deque

import numpy as np

from h2o3_tpu.analysis.lockdep import make_lock
from h2o3_tpu.obs import metrics as _om
from h2o3_tpu.utils.env import env_bool, env_int
from h2o3_tpu.obs import timeline as _tl

TIER_HBM = "hbm"
TIER_HOST = "host"
TIER_DISK = "disk"


def _hbm_budget_bytes() -> int:
    return env_int("H2O3_TPU_HBM_BUDGET_MB", 0) * 2**20


def _host_budget_bytes() -> int:
    return env_int("H2O3_TPU_HOST_BUDGET_MB", 0) * 2**20


def _fetch_dev_planes(dev):
    """(data_np, mask_np|None) via explicit device_get of both planes —
    the one spelling of the device→host fetch shared by staging,
    demotion and host_view (transfer-guard-clean)."""
    import jax
    data, mask = dev
    return (np.asarray(jax.device_get(data)),
            None if mask is None else np.asarray(jax.device_get(mask)))


TIER_FAULTS = _om.counter(
    "h2o3_dkv_tier_faults_total",
    "chunk promotions through the DKV tier ladder, labeled by the tier "
    "the chunk was faulted FROM (host = device_put of resident codec "
    "bytes, disk = spill-file load + device_put)")
TIER_EVICTIONS = _om.counter(
    "h2o3_dkv_tier_evictions_total",
    "chunk demotions through the DKV tier ladder, labeled by the tier "
    "the chunk was evicted TO (host = device buffers freed, disk = "
    "codec bytes spilled under ice_root)")


class TierChunk:
    """One pageable plane bundle: a Vec's packed data + optional NA mask.

    Write-once payload (Vecs are immutable after ingest; column mutation
    replaces the whole Vec), so tier copies never diverge: the device
    planes, the host codec bytes and the spill file all encode the same
    values and any of them can be dropped once a colder copy exists.
    """

    __slots__ = ("key", "nbytes", "rows", "pinned", "put", "_dev",
                 "_host", "_path", "_io", "_last", "_prefetched",
                 "__weakref__")

    def __init__(self, key: str, dev=None, host=None, put: str = "rows"):
        self.key = key
        # promotion placement: "rows" row-shards dim 0 over the mesh
        # (dense planes, padded to the device count); "flat" places on
        # the default device (SparseVec nz planes — their length is the
        # nnz count, not row-aligned, and consumers concatenate them)
        self.put = put
        data, mask = dev if dev is not None else host
        self.rows = int(data.shape[0])
        self.nbytes = int(np.prod(data.shape)) * data.dtype.itemsize
        if mask is not None:
            self.nbytes += int(np.prod(mask.shape)) * mask.dtype.itemsize
        self.pinned = 0
        self._dev = dev            # None = born cold (budgeted ingest:
        #                            the planes wait in the host tier and
        #                            the first access faults them in)
        self._host = host          # (packed np, mask np | None) | None
        self._path = None          # spill file when disk-resident
        # one lockdep class for every chunk's transfer lock: the pager
        # never holds two at once, so instances are interchangeable
        self._io = make_lock("tiering.io")
        self._last = 0
        self._prefetched = False

    @property
    def tier(self) -> str:
        """Warmest tier holding this chunk's planes."""
        if self._dev is not None:
            return TIER_HBM
        if self._host is not None:
            return TIER_HOST
        return TIER_DISK

    def device(self):
        """(data, mask) jax.Arrays — THE read path for Vec.data/Vec.mask.
        Resident chunks cost one attribute read + an LRU stamp; anything
        colder faults through the pager."""
        dev = self._dev
        if dev is not None:
            self._last = PAGER.tick()
            if self._prefetched:
                self._prefetched = False
                PAGER.count_prefetch_hit()
            return dev
        return PAGER.fault(self)

    def host_view(self):
        """(data, mask) packed numpy planes WITHOUT promoting to HBM —
        disk-resident chunks are loaded to the host tier; HBM-resident
        chunks with no host mirror are fetched (explicit device_get)."""
        host = self._host
        if host is not None:
            self._last = PAGER.tick()
            return host
        return PAGER.fault_host(self)

    def staging_view(self):
        """Packed numpy planes for host-side staging (the serving path):
        prefers the resident copy that costs the least — host bytes when
        they exist, one explicit device_get otherwise. Never promotes."""
        dev = self._dev
        if self._host is None and dev is not None:
            return _fetch_dev_planes(dev)
        return self.host_view()

    def __repr__(self):
        return f"<TierChunk {self.key} {self.tier} {self.nbytes}B>"


class ChunkPager:
    """The three-tier LRU pager; one per process, like the Cleaner."""

    def __init__(self):
        self._lock = make_lock("tiering.residency")
        self._chunks: dict[str, weakref.ref] = {}
        self._dead: deque = deque()      # keys whose chunk was GC'd;
        #                                  appended lock-free from weakref
        #                                  callbacks, reaped under _lock
        self._dead_paths: dict[str, str] = {}
        # O(1) occupancy accounting: last-known (tier, nbytes) per chunk
        # + running per-tier byte totals, adjusted under _lock at every
        # tier transition (_account_locked) — fault admission and peak
        # tracking must not scan the whole chunk map per fault
        self._acct: dict[str, tuple] = {}
        self._bytes = {TIER_HBM: 0, TIER_HOST: 0, TIER_DISK: 0}
        self._ids = itertools.count(1)
        self._ticks = itertools.count(1)
        self.hbm_budget = _hbm_budget_bytes()
        self.host_budget = _host_budget_bytes()
        self._reserved = 0       # bytes admitted but not yet landed: makes
        #                          budget admission atomic across
        #                          concurrent faults (consumer + prefetch)
        self._peak_hbm = 0
        self._prefetch_hits = 0
        self._prefetch_requests = 0
        self._fault_count = 0
        self._pf_q: queue.Queue = queue.Queue()
        self._pf_thread = None

    # ---- config ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        """Tiering active: a budget is set, or forced via H2O3_TPU_TIERING
        (retains host codec mirrors at ingest so demotion is free)."""
        return bool(self.hbm_budget or self.host_budget
                    or env_bool("H2O3_TPU_TIERING", False))

    @property
    def ingest_cold(self) -> bool:
        """Park newly-ingested packed planes in the HOST tier (born
        cold, no device_put at parse): always under an HBM budget — an
        eager put would spike past it before the pager could act — and
        opt-in via H2O3_TPU_INGEST_COLD for budget-less runs that still
        want spike-free bulk ingest (the distributed-parse coordinator
        of a multi-controller cloud, where a device_put of globally
        sharded planes from one process would wedge a collective)."""
        return bool(self.hbm_budget
                    or env_bool("H2O3_TPU_INGEST_COLD", False))

    def tick(self) -> int:
        return next(self._ticks)

    def count_prefetch_hit(self):
        self._prefetch_hits += 1

    # ---- registration ----------------------------------------------------
    def new_chunk(self, data, mask, host=None, label: str = "",
                  pinned: int = 0, put: str = "rows") -> TierChunk:
        """Wrap freshly-ingested planes and register with the pager.
        `data` may be None when only packed host bytes exist (budgeted
        ingest parks new chunks in the host tier — an eager device_put
        would spike HBM past the budget before the pager could act).
        `pinned` pins BEFORE registration: incrementing after new_chunk
        returns leaves a window where _enforce_budgets() below could pick
        the brand-new chunk as a demotion victim."""
        key = f"{label or 'chunk'}#{next(self._ids)}"
        dev = (data, mask) if data is not None else None
        ch = TierChunk(key, dev,
                       host=host if (self.enabled or dev is None)
                       else None, put=put)
        ch.pinned = pinned
        ch._last = self.tick()

        def _on_gc(_ref, _key=key, _pager=self):
            _pager._dead.append(_key)      # lock-free: may run mid-GC

        with self._lock:
            self._reap_locked()
            self._chunks[key] = weakref.ref(ch, _on_gc)
            self._account_locked(ch)
        self._enforce_budgets()       # light Cleaner wakeup: no snapshot
        return ch

    def _enforce_budgets(self):
        """Budget enforcement without maybe_demote's before/after chunk
        snapshot — this runs per Vec registration, and wide-frame ingest
        must not pay an O(live chunks) scan per column."""
        if self.hbm_budget:
            self._make_room(0)
        if self.host_budget:
            self._demote_host_tier()

    def _account_locked(self, ch: TierChunk):
        """Refresh the per-medium byte totals for `ch`; call under _lock
        at every residency transition. Accounting is PRESENCE-based: an
        HBM-resident chunk that also keeps its host codec mirror counts
        in BOTH hbm and host — the host budget must bound actual RAM,
        mirrors included."""
        present = (ch._dev is not None, ch._host is not None,
                   ch._path is not None)
        prev = self._acct.get(ch.key)   # h2o3-ok: R003 _locked helper — every caller holds self._lock
        if prev is not None:
            for tier, had in zip((TIER_HBM, TIER_HOST, TIER_DISK),
                                 prev[0]):
                if had:
                    self._bytes[tier] -= prev[1]
        self._acct[ch.key] = (present, ch.nbytes)   # h2o3-ok: R003 _locked helper — every caller holds self._lock
        for tier, has in zip((TIER_HBM, TIER_HOST, TIER_DISK), present):
            if has:
                self._bytes[tier] += ch.nbytes
        if present[0] and self._bytes[TIER_HBM] > self._peak_hbm:
            self._peak_hbm = self._bytes[TIER_HBM]   # h2o3-ok: R003 _locked helper — every caller holds self._lock

    def _reap_locked(self):
        while self._dead:
            key = self._dead.popleft()
            self._chunks.pop(key, None)   # h2o3-ok: R003 _locked helper — every caller holds self._lock (the weakref callback only appends to the lock-free _dead deque)
            acct = self._acct.pop(key, None)   # h2o3-ok: R003 _locked helper — every caller holds self._lock
            if acct is not None:
                for tier, had in zip((TIER_HBM, TIER_HOST, TIER_DISK),
                                     acct[0]):
                    if had:
                        self._bytes[tier] -= acct[1]
            path = self._dead_paths.pop(key, None)   # h2o3-ok: R003 _locked helper — every caller holds self._lock
            if path is not None:
                from h2o3_tpu.io import spill as _spill
                _spill.delete_chunk(path)

    def _live_locked(self) -> list:
        out = []
        for ref in list(self._chunks.values()):
            ch = ref()
            if ch is not None:
                out.append(ch)
        return out

    # ---- accounting ------------------------------------------------------
    def tier_bytes(self) -> dict:
        with self._lock:
            self._reap_locked()
            return dict(self._bytes)

    def peak_hbm_bytes(self) -> int:
        return self._peak_hbm

    def reset_peak(self):
        """Restart the HBM high-water mark (tests bracket a budgeted
        phase with this to prove occupancy stayed bounded THROUGHOUT)."""
        with self._lock:
            self._peak_hbm = self._bytes[TIER_HBM]

    def stats(self) -> dict:
        tb = self.tier_bytes()
        with self._lock:
            reserved = self._reserved
        return {"tier_bytes": tb, "hbm_budget": self.hbm_budget,
                "host_budget": self.host_budget,
                "reserved": reserved,
                "peak_hbm_bytes": self._peak_hbm,
                "faults": self._fault_count,
                "prefetch_requests": self._prefetch_requests,
                "prefetch_hits": self._prefetch_hits}

    def _device_in_use(self):
        """bytes_in_use from the obs device-memory gauge series — the
        real-HBM pressure signal. None on CPU (the process heap is not a
        paging target) or when the backend exposes no stats."""
        try:
            import jax
            if jax.default_backend() == "cpu":
                return None
            series = _om._device_memory_series()
        except Exception:   # noqa: BLE001 — no backend, no device signal
            return None
        total = sum(v for lbl, v in series
                    if lbl.get("kind") == "bytes_in_use")
        return total or None

    # ---- the ladder ------------------------------------------------------
    def _try_reserve(self, nbytes: int, force: bool = False) -> bool:
        """Atomically admit `nbytes` of incoming HBM occupancy against
        the budget (+ every other in-flight promotion's reservation).
        `force` admits regardless — out-of-core must make progress when
        nothing is demotable (e.g. one chunk larger than the budget)."""
        with self._lock:
            if force or not self.hbm_budget:
                self._reserved += nbytes
                return True
            if self._bytes[TIER_HBM] + self._reserved + nbytes \
                    <= self.hbm_budget:
                self._reserved += nbytes
                return True
        return False

    def _release_reservation(self, nbytes: int):
        with self._lock:
            self._reserved -= nbytes

    def fault(self, ch: TierChunk, _mark_prefetch: bool = False):
        """Promote a chunk to HBM: one bulk device_put of the packed
        planes (loading the spill file first when disk-resident).
        Admission is a reservation taken BEFORE the transfer, so
        concurrent faults (consumer thread + prefetch worker) cannot
        jointly overshoot the budget. The spill file (if any) is only
        deleted AFTER the promotion lands — a failed device_put must
        leave the chunk recoverable from disk."""
        src = ch.tier
        forced = False
        while True:
            with ch._io:
                dev = ch._dev
                if dev is not None:        # lost the race to another
                    return dev             # faulting thread: done
                if self._try_reserve(ch.nbytes, force=forced):
                    try:
                        data, mask = self._host_planes(ch)
                        if ch.put == "flat":
                            import jax.numpy as jnp
                            ddev = jnp.asarray(data)
                            dmask = None if mask is None \
                                else jnp.asarray(mask)
                        else:
                            from h2o3_tpu.parallel import mrtask as _mr
                            ddev = _mr.device_put_rows(data)
                            dmask = None if mask is None \
                                else _mr.device_put_rows(mask)
                        dev = (ddev, dmask)
                        with self._lock:
                            ch._dev = dev
                            ch._last = self.tick()
                            if self.enabled:
                                ch._host = (data, mask)  # host tier copy
                            else:
                                ch._host = None  # don't double RAM
                            path, ch._path = ch._path, None
                            self._dead_paths.pop(ch.key, None)
                            self._account_locked(ch)
                            if _mark_prefetch:
                                ch._prefetched = True
                    finally:
                        self._release_reservation(ch.nbytes)
                    if path is not None:
                        from h2o3_tpu.io import spill as _spill
                        _spill.delete_chunk(path)
                    break
            # over budget: demote outside the io lock (taking victims'
            # io locks under ours would deadlock opposing faults), then
            # retry; a fruitless pass forces admission so a chunk bigger
            # than the whole budget still faults
            forced = not self._make_room(ch.nbytes, exclude=ch)
        self._note_fault(ch, src)
        self._demote_host_tier()
        # the LOCAL tuple, not a re-read: a concurrent demotion may
        # already have nulled ch._dev, but these arrays stay valid (the
        # caller's reference keeps the buffers alive)
        return dev

    def fault_host(self, ch: TierChunk):
        """Ensure packed host planes exist (disk→host promotion, or an
        explicit fetch for device-born chunks) without touching HBM."""
        with ch._io:
            host = ch._host
            if host is not None:
                return host
            dev = ch._dev
            if dev is not None:
                # h2o3-ok: R008 per-chunk leaf transfer lock; the fetch IS the demotion payload (bounded by one plane)
                host = _fetch_dev_planes(dev)
            else:
                from h2o3_tpu.io import spill as _spill
                host = _spill.read_chunk(ch._path)
            stale = None
            with self._lock:
                ch._host = host
                if ch._dev is None:
                    # planes safely re-homed: only now may the spill
                    # file go (a failed load left everything intact)
                    stale, ch._path = ch._path, None
                    self._dead_paths.pop(ch.key, None)
                ch._last = self.tick()
                self._account_locked(ch)
            if stale is not None:
                from h2o3_tpu.io import spill as _spill
                _spill.delete_chunk(stale)
        if ch._dev is None:
            self._note_fault(ch, TIER_DISK, to_tier=TIER_HOST)
        # a disk→host promotion raises host occupancy too: enforce the
        # host budget here as well (the just-loaded chunk is MRU, so it
        # is the LAST candidate to go back down)
        self._demote_host_tier()
        return host        # local tuple: survives a concurrent demotion

    def demote(self, ch: TierChunk, to_tier: str):
        """Push a chunk down the ladder (hbm→host frees device buffers;
        host→disk writes the spill file and frees the host bytes)."""
        if to_tier not in (TIER_HOST, TIER_DISK):
            raise ValueError(f"demote target {to_tier!r}")
        with ch._io:
            moved = False
            if ch._dev is not None:
                if ch._host is None:
                    # h2o3-ok: R008 per-chunk leaf transfer lock; the fetch IS the demotion payload (bounded by one plane)
                    ch._host = _fetch_dev_planes(ch._dev)
                with self._lock:
                    ch._dev = None
                    self._account_locked(ch)
                moved = True
            if to_tier == TIER_DISK and ch._host is not None:
                from h2o3_tpu.io import spill as _spill
                data, mask = ch._host
                path = _spill.write_chunk(ch.key, data, mask)
                with self._lock:
                    ch._path = path
                    ch._host = None
                    self._dead_paths[ch.key] = path
                    self._account_locked(ch)
                moved = True
            elif to_tier == TIER_HOST and ch._path is not None \
                    and ch._host is None and ch._dev is None:
                return      # already colder than asked: leave on disk
        if moved:
            TIER_EVICTIONS.inc(tier=to_tier)
            sp = _tl.SPANS.current()
            if sp is not None:
                sp.event("dkv.tier_evict", chunk=ch.key, to=to_tier,
                         bytes=ch.nbytes)

    def _host_planes(self, ch: TierChunk):
        """Packed host planes for a fault; caller holds ch._io. Pure
        read: chunk state and the spill file are untouched, so an error
        in the caller's device_put leaves the chunk recoverable."""
        if ch._host is not None:
            return ch._host
        from h2o3_tpu.io import spill as _spill
        return _spill.read_chunk(ch._path)

    def _note_fault(self, ch: TierChunk, src: str, to_tier: str = TIER_HBM):
        self._fault_count += 1
        if src != to_tier:
            TIER_FAULTS.inc(tier=src)
        sp = _tl.SPANS.current()
        if sp is not None:
            sp.event("dkv.tier_fault", chunk=ch.key, src=src,
                     bytes=ch.nbytes)

    # ---- budget enforcement ---------------------------------------------
    def _victims_locked(self, tier: str, exclude) -> list:
        """Live, unpinned chunks on `tier`, coldest first."""
        out = [c for c in self._live_locked()
               if c.tier == tier and not c.pinned and c is not exclude]
        out.sort(key=lambda c: c._last)
        return out

    def _make_room(self, incoming: int, exclude=None) -> bool:
        """Demote LRU HBM chunks until `incoming` more bytes (plus every
        in-flight reservation) fit the budget — BEFORE the promotion
        lands, so accounted HBM occupancy never overshoots. Returns False
        when a pass made no progress (nothing demotable): the caller
        forces admission, since out-of-core must make progress even for a
        chunk larger than the whole budget."""
        if not self.hbm_budget:
            return True
        # device-pressure relief (non-chunk HBM — programs, params — over
        # budget): checked ONCE per pass and relieved by at most one LRU
        # demotion, never by draining the working set; non-chunk bytes
        # can exceed the budget permanently, and looping on that signal
        # would thrash every resident chunk on every fault
        dev = self._device_in_use()
        if dev is not None and dev > self.hbm_budget:
            with self._lock:
                vic = next(iter(self._victims_locked(TIER_HBM, exclude)),
                           None)
            if vic is not None:
                self.demote(vic, TIER_HOST)
        demoted = False
        while True:
            with self._lock:
                self._reap_locked()
                if self._bytes[TIER_HBM] + self._reserved + incoming \
                        <= self.hbm_budget:
                    return True
                vic = next(iter(self._victims_locked(TIER_HBM, exclude)),
                           None)
            if vic is None:
                return demoted
            self.demote(vic, TIER_HOST)
            demoted = True

    def _demote_host_tier(self):
        """Spill LRU host-tier chunks to disk while over the host budget."""
        if not self.host_budget:
            return
        while True:
            with self._lock:
                self._reap_locked()
                # budget judged against ALL host bytes — pinned chunks
                # and codec mirrors of HBM-resident chunks included
                # (pinning exempts from eviction, not accounting); only
                # unpinned host-holding chunks are candidates to go down
                if self._bytes[TIER_HOST] <= self.host_budget:
                    return
                cands = [c for c in self._live_locked()
                         if c._host is not None and not c.pinned]
                cands.sort(key=lambda c: c._last)
                vic = cands[0] if cands else None
            if vic is None:
                return
            if vic._dev is not None:
                # HBM-resident chunk: its host bytes are just a mirror,
                # re-fetchable from the device — drop it, don't demote
                self._drop_host_mirror(vic)
            else:
                self.demote(vic, TIER_DISK)

    def _drop_host_mirror(self, ch: TierChunk):
        """Free a device-resident chunk's host codec mirror (the cheap
        half of host-budget enforcement — no ladder movement)."""
        with ch._io:
            if ch._dev is None or ch._host is None:
                return
            with self._lock:
                ch._host = None
                self._account_locked(ch)

    def maybe_demote(self) -> list:
        """Enforce both budgets (the Cleaner wakeup); returns the keys of
        chunks demoted this pass. Free when no budget is set — this runs
        on every Vec registration, and the unbudgeted ingest path must
        not pay a full chunk-map scan per column."""
        if not (self.hbm_budget or self.host_budget):
            return []
        before = {}
        with self._lock:
            self._reap_locked()
            for c in self._live_locked():
                before[c.key] = (c, c.tier)
        self._make_room(0)
        self._demote_host_tier()
        return [k for k, (c, t) in before.items() if c.tier != t]

    # ---- frame-level hooks (DKV.get / memory manager) --------------------
    def touch_chunks(self, chunks):
        for ch in chunks:
            if ch is not None:
                ch._last = self.tick()

    def on_frame_get(self, chunks):
        """DKV.get hook: LRU-touch, and when EVERY chunk sits on disk
        (a whole-frame spill) promote the codec bytes back to host RAM —
        the transparent-reload half of Value.java's duality; HBM faults
        stay lazy and chunk-granular on first access."""
        chunks = [c for c in chunks if c is not None]
        if not chunks:
            return
        self.touch_chunks(chunks)
        if all(c.tier == TIER_DISK for c in chunks):
            for c in chunks:
                c.host_view()

    # ---- prefetch (the MRTask lookahead) ---------------------------------
    def prefetch(self, handles):
        """Queue chunk tier-ups on the I/O worker so the NEXT chunk's
        promotion overlaps the CURRENT chunk's compute. Accepts TierChunks
        or objects carrying one as `_chunk` (Vecs). Fire-and-forget: a
        prefetch failure just means the consumer faults synchronously."""
        started = False
        for h in handles:
            ch = getattr(h, "_chunk", h)
            if not isinstance(ch, TierChunk) or ch._dev is not None:
                continue
            self._prefetch_requests += 1
            self._pf_q.put(weakref.ref(ch))
            started = True
        if started:
            self._ensure_worker()

    def _ensure_worker(self):
        with self._lock:
            if self._pf_thread is not None and self._pf_thread.is_alive():
                return
            t = threading.Thread(target=self._pf_loop, daemon=True,
                                 name="h2o3-tier-prefetch")
            self._pf_thread = t
            # started INSIDE the lock: a racing caller must observe the
            # new thread as alive, or it would spawn a duplicate
            # immortal worker
            t.start()

    def _pf_loop(self):
        while True:
            ref = self._pf_q.get()
            ch = ref()
            if ch is None or ch._dev is not None:
                continue
            try:
                # _mark_prefetch: the hit flag is set inside fault() only
                # when THIS call performed the promotion — losing the
                # race to a synchronous consumer fault must not count as
                # a prefetch hit
                self.fault(ch, _mark_prefetch=True)
            except Exception:   # noqa: BLE001 — consumer faults sync instead
                pass


PAGER = ChunkPager()


def _tier_bytes_series():
    tb = PAGER.tier_bytes()
    return [({"tier": t}, float(b)) for t, b in sorted(tb.items())]


TIER_BYTES = _om.gauge(
    "h2o3_dkv_tier_bytes",
    "packed chunk bytes resident per DKV tier (hbm = device planes, "
    "host = codec bytes in RAM, disk = spill files under ice_root)",
    fn=_tier_bytes_series)
