"""The "cloud": a device mesh replacing H2O-3's gossip/Paxos cluster.

Reference: water/Paxos.java, water/H2O.java:1845 (startLocalNode),
water/HeartBeatThread.java. H2O forms a cloud of symmetric JVM peers via UDP
gossip and freezes membership at the first DKV write (Paxos.java:145).

TPU-native design: JAX is single-controller — one Python process drives every
chip. "Cloud formation" is simply constructing a `jax.sharding.Mesh` over the
visible devices; there is no consensus protocol to run, no heartbeats, no
flatfiles. Membership is fixed by construction (the moral equivalent of
`Paxos.lockCloud`), and "nodes" are mesh shards addressed by named axes.

Axes:
  * "rows"  — the data axis. Frames are row-sharded over it; every MRTask-like
              reduce becomes a psum over this axis riding ICI.
  * "model" — optional second axis for tensor/model parallelism (DeepLearning
              wide layers, batched tree-building, grid-search fan-out).
"""

from __future__ import annotations

import math
import os
import re
import threading
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS = "rows"
MODEL = "model"

# RLock: cloud() calls init() while already holding the lock (first-use
# formation path) — a plain Lock deadlocks every standalone server start
_lock = threading.RLock()
_CLOUD: "Cloud | None" = None


@dataclass
class Cloud:
    """A formed cloud == a live device mesh plus derived shardings."""

    mesh: Mesh
    name: str = "h2o3-tpu"
    # elastic membership (deploy/membership) epoch this mesh was built
    # for. The jax device runtime is fixed-size (ROADMAP gap), so an
    # epoch bump rebuilds the mesh over the SAME visible devices — but a
    # fresh Mesh object per epoch gives downstream placement caches (the
    # serving param store) an identity to invalidate against.
    epoch: int = 1

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def n_rows_shards(self) -> int:
        return self.mesh.shape[ROWS]

    @property
    def n_model_shards(self) -> int:
        return self.mesh.shape.get(MODEL, 1)

    # ---- shardings ------------------------------------------------------
    def rows_sharding(self, ndim: int = 1) -> NamedSharding:
        """Row-sharded: dim 0 split over the data axis, rest replicated."""
        spec = P(ROWS, *([None] * (ndim - 1)))
        return NamedSharding(self.mesh, spec)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    # ---- row padding ----------------------------------------------------
    # H2O lays rows out via ESPC (Vec.java:163-171): uneven chunks per node.
    # XLA wants even, static shapes: we pad the row count up to a multiple of
    # (row-shards × sublane granule) and carry the logical nrows separately.
    ROW_GRANULE = 8  # f32 sublane granularity on TPU

    def padded_rows(self, nrows: int) -> int:
        g = self.n_rows_shards * self.ROW_GRANULE
        return max(g, int(math.ceil(nrows / g)) * g)

    def describe(self) -> dict:
        return {
            "cloud_name": self.name,
            "cloud_size": self.n_devices,
            "mesh_shape": dict(self.mesh.shape),
            "devices": [str(d) for d in self.mesh.devices.flat],
            "platform": self.mesh.devices.flat[0].platform if self.n_devices else "?",
            "consensus": "locked",  # single-controller: always formed, always locked
        }


def init(n_rows_shards: int | None = None, n_model_shards: int = 1,
         devices=None, name: str | None = None) -> Cloud:
    """Form the cloud (h2o.init analog). Idempotent unless shape changes.
    Name: explicit arg > ai.h2o.cloud.name property (-name flag) >
    default."""
    global _CLOUD
    if name is None:
        from h2o3_tpu.utils import config as _cfg
        name = str(_cfg.get_property("cloud.name", None) or "h2o3-tpu")
    with _lock:
        devices = list(devices if devices is not None else jax.devices())
        total = len(devices)
        if n_rows_shards is None:
            n_rows_shards = total // n_model_shards
        use = n_rows_shards * n_model_shards
        if use > total:
            raise ValueError(
                f"requested {use} devices ({n_rows_shards}x{n_model_shards}) "
                f"but only {total} visible")
        dev_grid = np.array(devices[:use]).reshape(n_rows_shards, n_model_shards)
        mesh = Mesh(dev_grid, (ROWS, MODEL))
        _CLOUD = Cloud(mesh=mesh, name=name)
        # extension lifecycle (ExtensionManager onLocalNodeStarted analog)
        try:
            from h2o3_tpu.ext import load_configured_extensions
            load_configured_extensions(_CLOUD)
        except Exception:   # an extension failure must not kill the cloud
            import traceback
            traceback.print_exc()
        return _CLOUD


def cloud() -> Cloud:
    """Return the formed cloud, forming a default one on first use."""
    global _CLOUD
    if _CLOUD is None:
        with _lock:
            if _CLOUD is None:
                init()
    return _CLOUD


def shutdown():
    """Tear down the cloud and the registry (h2o.cluster().shutdown())."""
    global _CLOUD
    from h2o3_tpu.core.kvstore import DKV
    with _lock:
        DKV.clear()
        _CLOUD = None


def cluster_info() -> dict:
    """REST /3/Cloud analog."""
    return cloud().describe()


def note_epoch(epoch: int) -> "Cloud":
    """Adopt a cloud-membership epoch (deploy/membership listener hook):
    when it moves past the formed mesh's epoch, rebuild the mesh — same
    shape, same visible devices (the jax runtime is fixed-size), but a
    NEW Mesh object stamped with the epoch, so placement caches keyed on
    mesh identity (serving/params) re-place instead of serving arrays
    laid out for a dead membership. Idempotent for old/equal epochs."""
    global _CLOUD
    with _lock:
        c = cloud()
        if epoch <= c.epoch:
            return c
        mesh = Mesh(c.mesh.devices, c.mesh.axis_names)
        _CLOUD = Cloud(mesh=mesh, name=c.name, epoch=int(epoch))
        return _CLOUD


# ---------------------------------------------------------------------------
# Regex-rule partitioner: param pytrees → PartitionSpec pytrees →
# NamedSharding placements (the match_partition_rules / shard_params /
# make_shard_and_gather_fns pattern, re-keyed for model serving).
#
# A rule set is ((regex, PartitionSpec), ...). Each leaf of a param
# pytree is named by its '/'-joined tree path ("_trees/value",
# "_params_net/1/0", …); the FIRST rule whose regex `re.search`-matches
# the name wins. Scalars and unmatched leaves replicate (P()) — serving
# must never refuse a model because a rule is missing; replication is
# the always-correct default and still yields ONE shared copy per model
# (the HBM win is vs. per-bucket baked constants, not vs. replication).


def _leaf_name(path) -> str:
    """'/'-joined jax KeyPath → rule-matchable leaf name."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def named_tree_map(fn, tree):
    """tree_map with the '/'-joined path name as the first argument."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: fn(_leaf_name(path), leaf), tree)


def match_partition_rules(rules, params):
    """Pytree of PartitionSpec, one per leaf of `params`, by first-match
    regex over the leaf's path name. Scalar leaves and leaves no rule
    matches get P() (replicated)."""
    rules = tuple(rules or ())

    def spec_for(name, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()            # never partition scalars
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        return P()
    return named_tree_map(spec_for, params)


def _canon_host_leaf(leaf) -> np.ndarray:
    """Serving dtype canonicalization for HOST leaves: params reach the
    scorer in the dtypes its traced math uses — f32 floats, i32 ints.
    Matches the jnp.asarray(..., jnp.float32) casts inside every
    _score_matrix, so passing params as device args instead of baked
    constants cannot change a single bit of the result."""
    a = np.asarray(leaf)
    if a.dtype == np.float64:
        a = a.astype(np.float32)
    elif a.dtype == np.int64:
        a = a.astype(np.int32)
    return a


def shard_params(params, specs=None, *, cld: "Cloud | None" = None,
                 rules=None):
    """device_put every leaf of a param pytree with its NamedSharding —
    ONE resident copy per model, shared by every compiled row-bucket
    program that takes it as an argument. `specs` is a PartitionSpec
    pytree (from match_partition_rules); passing `rules` computes it.
    Device-resident leaves (trained ensembles) reshard device-to-device
    — no host round trip, transfer-guard clean. Multi-controller
    runtimes build each process's addressable shards from its own
    (replay-identical) host copy, exactly like mrtask.device_put_rows."""
    c = cld or cloud()
    if specs is None:
        specs = match_partition_rules(rules, params)
    multi = jax.process_count() > 1

    def place(leaf, spec):
        sh = NamedSharding(c.mesh, spec)
        if multi:
            from h2o3_tpu.parallel import mrtask as _mrt
            arr = _canon_host_leaf(
                _mrt.host_fetch(leaf) if isinstance(leaf, jax.Array)
                else leaf)
            return jax.make_array_from_callback(arr.shape, sh,
                                                lambda idx: arr[idx])
        if isinstance(leaf, jax.Array):
            return jax.device_put(leaf, sh)
        return jax.device_put(_canon_host_leaf(leaf), sh)
    return jax.tree_util.tree_map(place, params, specs)


def make_shard_and_gather_fns(specs, cld: "Cloud | None" = None):
    """(shard_fn, gather_fn) pytrees for a PartitionSpec pytree:
    shard_fn(leaf) places one leaf with its NamedSharding; gather_fn
    fetches it back to a host numpy array (the checkpoint/export hop)."""
    c = cld or cloud()

    def mk_shard(spec):
        return lambda leaf: shard_params(leaf, specs=spec, cld=c)

    def mk_gather(spec):
        del spec
        from h2o3_tpu.parallel import mrtask as _mrt
        return lambda leaf: _mrt.host_fetch(leaf)
    return (jax.tree_util.tree_map(mk_shard, specs,
                                   is_leaf=lambda s: isinstance(s, P)),
            jax.tree_util.tree_map(mk_gather, specs,
                                   is_leaf=lambda s: isinstance(s, P)))


def params_nbytes(params) -> int:
    """Logical bytes of ONE copy of a (placed or host) param pytree —
    the h2o3_scorer_params_bytes gauge's unit: per-model HBM occupancy
    that must stay CONSTANT in the number of compiled row-buckets."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        n = getattr(leaf, "nbytes", None)
        if n is None:
            n = np.asarray(leaf).nbytes
        total += int(n)
    return total
