"""The "cloud": a device mesh replacing H2O-3's gossip/Paxos cluster.

Reference: water/Paxos.java, water/H2O.java:1845 (startLocalNode),
water/HeartBeatThread.java. H2O forms a cloud of symmetric JVM peers via UDP
gossip and freezes membership at the first DKV write (Paxos.java:145).

TPU-native design: JAX is single-controller — one Python process drives every
chip. "Cloud formation" is simply constructing a `jax.sharding.Mesh` over the
visible devices; there is no consensus protocol to run, no heartbeats, no
flatfiles. Membership is fixed by construction (the moral equivalent of
`Paxos.lockCloud`), and "nodes" are mesh shards addressed by named axes.

Axes:
  * "rows"  — the data axis. Frames are row-sharded over it; every MRTask-like
              reduce becomes a psum over this axis riding ICI.
  * "model" — optional second axis for tensor/model parallelism (DeepLearning
              wide layers, batched tree-building, grid-search fan-out).
"""

from __future__ import annotations

import math
import os
import threading
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS = "rows"
MODEL = "model"

# RLock: cloud() calls init() while already holding the lock (first-use
# formation path) — a plain Lock deadlocks every standalone server start
_lock = threading.RLock()
_CLOUD: "Cloud | None" = None


@dataclass
class Cloud:
    """A formed cloud == a live device mesh plus derived shardings."""

    mesh: Mesh
    name: str = "h2o3-tpu"

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def n_rows_shards(self) -> int:
        return self.mesh.shape[ROWS]

    @property
    def n_model_shards(self) -> int:
        return self.mesh.shape.get(MODEL, 1)

    # ---- shardings ------------------------------------------------------
    def rows_sharding(self, ndim: int = 1) -> NamedSharding:
        """Row-sharded: dim 0 split over the data axis, rest replicated."""
        spec = P(ROWS, *([None] * (ndim - 1)))
        return NamedSharding(self.mesh, spec)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    # ---- row padding ----------------------------------------------------
    # H2O lays rows out via ESPC (Vec.java:163-171): uneven chunks per node.
    # XLA wants even, static shapes: we pad the row count up to a multiple of
    # (row-shards × sublane granule) and carry the logical nrows separately.
    ROW_GRANULE = 8  # f32 sublane granularity on TPU

    def padded_rows(self, nrows: int) -> int:
        g = self.n_rows_shards * self.ROW_GRANULE
        return max(g, int(math.ceil(nrows / g)) * g)

    def describe(self) -> dict:
        return {
            "cloud_name": self.name,
            "cloud_size": self.n_devices,
            "mesh_shape": dict(self.mesh.shape),
            "devices": [str(d) for d in self.mesh.devices.flat],
            "platform": self.mesh.devices.flat[0].platform if self.n_devices else "?",
            "consensus": "locked",  # single-controller: always formed, always locked
        }


def init(n_rows_shards: int | None = None, n_model_shards: int = 1,
         devices=None, name: str | None = None) -> Cloud:
    """Form the cloud (h2o.init analog). Idempotent unless shape changes.
    Name: explicit arg > ai.h2o.cloud.name property (-name flag) >
    default."""
    global _CLOUD
    if name is None:
        from h2o3_tpu.utils import config as _cfg
        name = str(_cfg.get_property("cloud.name", None) or "h2o3-tpu")
    with _lock:
        devices = list(devices if devices is not None else jax.devices())
        total = len(devices)
        if n_rows_shards is None:
            n_rows_shards = total // n_model_shards
        use = n_rows_shards * n_model_shards
        if use > total:
            raise ValueError(
                f"requested {use} devices ({n_rows_shards}x{n_model_shards}) "
                f"but only {total} visible")
        dev_grid = np.array(devices[:use]).reshape(n_rows_shards, n_model_shards)
        mesh = Mesh(dev_grid, (ROWS, MODEL))
        _CLOUD = Cloud(mesh=mesh, name=name)
        # extension lifecycle (ExtensionManager onLocalNodeStarted analog)
        try:
            from h2o3_tpu.ext import load_configured_extensions
            load_configured_extensions(_CLOUD)
        except Exception:   # an extension failure must not kill the cloud
            import traceback
            traceback.print_exc()
        return _CLOUD


def cloud() -> Cloud:
    """Return the formed cloud, forming a default one on first use."""
    global _CLOUD
    if _CLOUD is None:
        with _lock:
            if _CLOUD is None:
                init()
    return _CLOUD


def shutdown():
    """Tear down the cloud and the registry (h2o.cluster().shutdown())."""
    global _CLOUD
    from h2o3_tpu.core.kvstore import DKV
    with _lock:
        DKV.clear()
        _CLOUD = None


def cluster_info() -> dict:
    """REST /3/Cloud analog."""
    return cloud().describe()
