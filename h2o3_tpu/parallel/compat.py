"""JAX version compatibility shims + the host-mesh collective guard.

The repo targets the stable `jax.shard_map` API (jax >= 0.6, `check_vma`
kwarg); older runtimes ship the same transform as
`jax.experimental.shard_map.shard_map` with the replication check under
`check_rep`. Resolving per call (not at import) keeps the module usable
when jax itself is stubbed out.

Host-mesh collective guard — THE one serialization point for concurrent
multi-replica dispatch on host (CPU) meshes. XLA's CPU client shares ONE
collective thread pool across concurrently launched programs: two
in-flight multi-replica executions each park a subset of their
participants at the rendezvous (collective_ops_utils.h "may be stuck")
and starve each other forever. The fix is to keep AT MOST ONE collective
program in flight: every dispatch funnel acquires the guard, launches,
and `block_until_ready`s BEFORE releasing — scoped to device execution
only, so host-side work (staging, binning prep, numpy solves) between
dispatches overlaps freely across threads. This hoists the whole-train
lock H2OGridSearch used to carry (models/grid.py) into the shared
dispatch layer: wired at mrtask dispatch (map_reduce/map_chunks/
cached_jit), the tree engine's per-level launches, and GLM's IRLS device
passes. Accelerator runtimes queue per-device and interleave fine, so
the guard is a no-op there (and on single-device CPU).
"""

from __future__ import annotations

import contextlib
import threading

import jax

from h2o3_tpu.utils import env as _uenv


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma=False):
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


# ---------------------------------------------------------------------------
# host-mesh collective serialization
# RLock: a guarded region may re-enter (tracing a guarded dispatch can
# evaluate nested cached_jit call sites on the same thread)
_HOST_COLLECTIVE_LOCK = threading.RLock()
_NEEDS_SERIALIZATION: bool | None = None


def needs_host_serialization() -> bool:
    """True on multi-device host (CPU) meshes, where XLA's shared
    collective thread pool makes concurrent multi-replica programs
    deadlock-prone. Memoized after the first backend probe;
    H2O3_HOST_SERIALIZE=0|1 overrides."""
    global _NEEDS_SERIALIZATION
    env = _uenv.env_str("H2O3_HOST_SERIALIZE", "")
    if env in ("0", "1"):
        return env == "1"
    if _NEEDS_SERIALIZATION is None:
        try:
            _NEEDS_SERIALIZATION = (jax.default_backend() == "cpu"
                                    and jax.device_count() > 1)
        except Exception:   # noqa: BLE001 — no backend: nothing to guard
            _NEEDS_SERIALIZATION = False
    return _NEEDS_SERIALIZATION


def host_collective_guard():
    """Context manager for a launch→block region on host meshes (a
    shared nullcontext elsewhere). Callers that hold device results
    across host-side work should prefer `run_host_serialized`, which
    also drains the launched program before releasing."""
    if needs_host_serialization():
        return _HOST_COLLECTIVE_LOCK
    return contextlib.nullcontext()


def _block_concrete(out):
    """block_until_ready on every CONCRETE array leaf (tracers pass
    through — a guarded dispatch evaluated under an outer trace must not
    try to force an abstract value)."""
    for leaf in jax.tree_util.tree_leaves(out):
        if isinstance(leaf, jax.Array) \
                and not isinstance(leaf, jax.core.Tracer):
            leaf.block_until_ready()
    return out


def run_host_serialized(fn):
    """Run `fn()` (a device launch) with at most one collective program
    in flight on host meshes: acquire the guard, launch, block until the
    result is ready, release. On accelerators: just `fn()` — async
    dispatch stays fully pipelined."""
    if not needs_host_serialization():
        return fn()
    with _HOST_COLLECTIVE_LOCK:
        # h2o3-ok: R008 the block IS the guard's contract — at most one collective program in flight means holding the lock through launch→ready; a stall here is exactly what the watchdog's device watch diagnoses
        return _block_concrete(fn())


_usage_mod = None


def _usage():
    """Lazy obs/usage handle (same shape as mrtask's lazy qos import):
    the metrics registry pulls usage in at its own import, so a
    module-level import here would cycle through obs during bootstrap;
    by the first guarded launch the graph is settled."""
    global _usage_mod
    if _usage_mod is None:
        from h2o3_tpu.obs import usage
        _usage_mod = usage
    return _usage_mod


def guard_collective(jfn):
    """Wrap an already-jitted callable so every invocation runs under
    the host-mesh collective guard. The decorator spelling of
    run_host_serialized, for module-level jits the dispatch layer cannot
    see (the tree engine's level programs, GLM's gram passes).

    Also the bottom of the usage-attribution funnel: every guarded
    launch meters its wall seconds to the ambient principal (kind
    `jit`) unless an outer meter — mrtask's traced dispatch, the scorer
    cache — already owns the charge."""
    import functools

    @functools.wraps(jfn)
    def _guarded(*a, **k):
        with _usage().meter("jit"):
            return run_host_serialized(lambda: jfn(*a, **k))

    _guarded.__wrapped__ = jfn
    return _guarded


def guarded_jit(fn, **jit_kwargs):
    """jax.jit + guard_collective in one step (the analyzer's rules_jax
    treats this as a jit-maker, so R001/R004 coverage is preserved)."""
    return guard_collective(jax.jit(fn, **jit_kwargs))


# Whole-train serialization on host meshes. The fine-grained guard above
# covers every JIT launch, but a training body also runs EAGER ops on
# sharded arrays (e.g. shared_tree._binned_setup's row slicing → gather
# collectives) that no call-site wrapper can reach — two concurrent
# trains' eager collectives still rendezvous-starve (reproduced: the
# parallel grid probe hangs ~50% without this). So concurrent TRAINS
# serialize end-to-end on host meshes, exactly the protection the old
# models/grid.py lock gave — now owned by the shared layer so any
# concurrent-train driver (grid, future tuners) gets it. Accelerator
# runtimes keep full overlap (nullcontext). RLock: nested drivers
# (AutoML → grid → train) re-enter on one thread.
_TRAIN_LOCK = threading.RLock()


def train_guard():
    """Context manager serializing one whole model-train body against
    concurrent trains on host meshes; nullcontext elsewhere."""
    if needs_host_serialization():
        return _TRAIN_LOCK
    return contextlib.nullcontext()
