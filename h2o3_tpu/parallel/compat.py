"""JAX version compatibility shims for the parallel layer.

The repo targets the stable `jax.shard_map` API (jax >= 0.6, `check_vma`
kwarg); older runtimes ship the same transform as
`jax.experimental.shard_map.shard_map` with the replication check under
`check_rep`. Resolving per call (not at import) keeps the module usable
when jax itself is stubbed out.
"""

from __future__ import annotations

import jax


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma=False):
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)
