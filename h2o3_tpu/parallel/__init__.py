from h2o3_tpu.parallel.mesh import Cloud, init, cloud, shutdown
from h2o3_tpu.parallel.mrtask import (map_reduce, shard_sum, map_chunks,
                                      map_chunked, prefetch_chunks)
