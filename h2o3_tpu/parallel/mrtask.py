"""The compute substrate: H2O's MRTask re-imagined for XLA.

Reference: water/MRTask.java:65 — serialize a task, fan it out over nodes in a
binary RPC tree (MRTask.java:690-754), fork-join down to one chunk per task,
run `map(Chunk[])`, then `reduce` partial POJOs back up two trees
(MRTask.java:850-921).

TPU-native design: there is no task serialization, no RPC tree and no explicit
reduce plumbing. A "map over chunks + tree reduce" is exactly what XLA compiles
a jitted computation over a row-sharded array into: the map runs shard-local,
and any cross-shard reduction (sum/min/max/…) lowers to an ICI collective
(all-reduce) with optimal scheduling. Two entry points:

  * map_reduce(fn, ...)  — jit `fn` over sharded inputs with replicated (small)
    outputs. The common case: XLA inserts the collectives. This is the moral
    equivalent of `new MRTask(){map;reduce}.doAll(frame)`.
  * map_chunks(fn, ...)  — `shard_map` when per-shard (per-"node") semantics
    are required: fn sees its local row block and may call lax.psum etc.
    Equivalent of MRTask with setupLocal/postLocal node-level hooks.
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.deploy import chaos as _chaos
from h2o3_tpu.deploy import membership as _mb
from h2o3_tpu.obs import tracing as _tracing
from h2o3_tpu.obs import watchdog as _wd
from h2o3_tpu.obs.timeline import span as _span
from h2o3_tpu.parallel import compat as _compat
from h2o3_tpu.parallel import mesh as _mesh

# ---------------------------------------------------------------------------
# cached_jit: jax.jit keyed by CODE + closure VALUES, not function identity.
#
# jax's trace/compile cache is keyed on the function object, so
# `jax.jit(lambda x: ...)` (or a nested def) inside a function body mints a
# fresh identity per call and recompiles every invocation — the R001 bug
# class the static analyzer (h2o3_tpu/analysis) now rejects. A lambda
# EXPRESSION, however, compiles to one code object shared by every
# evaluation; keying the wrapper on (code, defaults, closure values) makes
# call-site closures hit one resident wrapper as long as their captured
# values are equal. Unhashable captures (arrays, models) fall back to a
# plain uncached jit — exactly today's behavior, never worse.
_JIT_CACHE: OrderedDict = OrderedDict()
_JIT_CACHE_MAX = 512
_JIT_CACHE_LOCK = threading.Lock()


class _Uncacheable(Exception):
    """Function cannot be keyed safely — caller must fall back to a
    plain (uncached) jax.jit."""


def _typed(v):
    """Cell/default values keyed WITH their type: 1, 1.0 and True hash
    equal but trace to different programs."""
    return (type(v), v)


def _fn_key(fn, _seen=None):
    """Identity-free cache key for a function: code + defaults + closure
    cell values, resolving function-valued cells recursively (a per-call
    lambda captured by another per-call closure must not leak identity
    back into the key). Raises _Uncacheable for bound methods (two
    instances share code + cells, but trace different state) and for
    cyclic closures (recursive nested defs)."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return fn                      # builtin / C function: stable object
    if getattr(fn, "__self__", None) is not None:
        # bound method: two instances share code + cells but trace
        # different state — cannot be keyed identity-free
        raise _Uncacheable("bound method: state lives on __self__")
    if _seen is None:
        _seen = set()
    if id(fn) in _seen:
        raise _Uncacheable("cyclic closure")
    _seen.add(id(fn))
    cells = tuple(
        _fn_key(c.cell_contents, _seen) if callable(c.cell_contents)
        else _typed(c.cell_contents)
        for c in (fn.__closure__ or ()))
    defaults = tuple(_typed(v) for v in (fn.__defaults__ or ()))
    kwdefaults = tuple(sorted((k, _typed(v)) for k, v in
                              (fn.__kwdefaults__ or {}).items()))
    return (code, defaults, kwdefaults, cells)


def cached_jit(fn, **jit_kwargs):
    """jax.jit with a wrapper cache keyed by _fn_key + jit kwargs.

    The per-call-closure fix: `cached_jit(lambda x: x @ R)` at a call site
    re-evaluated per request resolves to ONE wrapper (and one compiled
    program per shape) as long as the captured values hash equal.
    """
    try:
        key = (_fn_key(fn),
               tuple(sorted(jit_kwargs.items())))
        hash(key)
    except (TypeError, ValueError, _Uncacheable):
        # unhashable captures, bound methods, cyclic closures, or an
        # uninitialized cell (ValueError): uncached fallback — exactly
        # the pre-cached_jit behavior, never wrong results. Guarded like
        # the cached path: every cached_jit call site is a potential
        # multi-replica launch on a host mesh.
        return _compat.guard_collective(jax.jit(fn, **jit_kwargs))
    with _JIT_CACHE_LOCK:
        jfn = _JIT_CACHE.get(key)
        if jfn is not None:
            _JIT_CACHE.move_to_end(key)
            return jfn
    # the host-mesh collective guard rides INSIDE the cached wrapper, so
    # every call site of a cached_jit program serializes its launch→ready
    # window on CPU meshes (see parallel/compat.py)
    jfn = _compat.guard_collective(jax.jit(fn, **jit_kwargs))
    with _JIT_CACHE_LOCK:
        cur = _JIT_CACHE.setdefault(key, jfn)
        _JIT_CACHE.move_to_end(key)
        while len(_JIT_CACHE) > _JIT_CACHE_MAX:
            _JIT_CACHE.popitem(last=False)
    return cur


def _dispatch_once(jfn, arrays):
    """One device launch. The host-mesh collective guard rides INSIDE
    `jfn` (cached_jit / map_chunks wrap their jits with guard_collective
    at creation), so this funnel adds no second lock acquisition. The
    chaos hook lets the fault harness fail a seeded dispatch with
    EpochChanged."""
    _chaos.maybe_raise("mrtask.dispatch", exc=_mb.EpochChanged)
    return jfn(*arrays)


def _dispatch_retrying(jfn, arrays, retryable: bool):
    """Membership-aware dispatch: an execution that straddles a cloud
    epoch bump (a worker excised mid-collective) retries ONCE against
    the new epoch with jittered backoff instead of failing the caller.
    Single-host clouds and donated-buffer dispatches (whose inputs are
    consumed by the first attempt) skip straight through."""
    if retryable and _mb.MEMBERSHIP.multi:
        return _mb.retry_once(lambda: _dispatch_once(jfn, arrays),
                              op="mrtask")
    return _dispatch_once(jfn, arrays)


_qos_mod = None
_usage_mod = None


def _qos():
    """Lazy, cached serving/qos handle: the serving package imports this
    module at load time, so a module-level import here would cycle; by
    the first device dispatch the import graph is settled and the cost
    is one `is None` check per call."""
    global _qos_mod
    if _qos_mod is None:
        from h2o3_tpu.serving import qos
        _qos_mod = qos
    return _qos_mod


def _usage():
    """Lazy obs/usage handle — same cycle-avoidance shape as _qos()."""
    global _usage_mod
    if _usage_mod is None:
        from h2o3_tpu.obs import usage
        _usage_mod = usage
    return _usage_mod


def _traced_dispatch(name: str, jfn, arrays, fn, retryable=True):
    """Dispatch `jfn(*arrays)`, recording an mrtask phase span when the
    calling thread is inside an active trace (obs/tracing). Untraced
    callers — training inner loops, bench — pay the trace TLS read plus
    one watchdog registration (a slotted dict insert/remove under a
    leaf lock, a few microseconds).

    Priority lanes (serving/qos): a dispatch issued from a Job thread is
    BATCH work — it defers (bounded by H2O3_QOS_BATCH_YIELD_S) while
    interactive scoring requests are pending in the micro-batch queue,
    so training never steals device slots out from under a waiting
    user. Preemption happens here, at the scheduler; an in-flight
    device program always runs to completion.

    Every dispatch is watchdog-watched: a device program blocked past
    H2O3_WATCHDOG_STALL_S (the XLA:CPU collective-rendezvous deadlock —
    two in-flight multi-replica executions starving each other's
    thread-pool slots) trips a pinned diagnostic trace with a cluster
    JStack instead of hanging the process silently."""
    _qos().batch_yield()
    fname = getattr(fn, "__name__", "<fn>")
    # usage attribution: the dispatch wall charges the ambient principal
    # under this op's kind; the guarded jit's own meter inside jfn is
    # suppressed (outermost meter wins), so the seconds charge once
    with _wd.watch("device", desc=f"{name}:{fname}"), \
            _usage().meter(name):
        if _tracing.current() is not None:
            with _span(name, fn=fname):
                return _dispatch_retrying(jfn, arrays, retryable)
        return _dispatch_retrying(jfn, arrays, retryable)


def prefetch_chunks(handles):
    """Start tier-up of DKV chunk handles (Vecs or TierChunks) on the
    pager's I/O worker — fire-and-forget, so a later fault finds the
    planes already HBM-resident. The MRTask lookahead primitive."""
    if not handles:
        return
    from h2o3_tpu.core import tiering as _tiering
    _tiering.PAGER.prefetch(handles)


def map_chunked(fn, chunks, *, lookahead: int = 1):
    """Sequential MRTask over out-of-core chunk handles: run `fn(chunk)`
    per handle, prefetching the NEXT `lookahead` handles' tier-up on the
    pager's I/O thread overlapped with the current handle's compute —
    the Cleaner-era "reload while the map runs" pipelining, chunk-shaped.
    Returns the list of per-chunk results (reduce is the caller's fold)."""
    seq = list(chunks)
    out = []
    queued = 0          # high-water mark: windows overlap, enqueue once
    for i, c in enumerate(seq):
        if lookahead > 0 and i + 1 < len(seq):
            lo = max(queued, i + 1)
            hi = i + 1 + lookahead
            if hi > lo:
                prefetch_chunks(seq[lo:hi])
                queued = hi
        out.append(fn(c))
    return out


def map_reduce(fn, *arrays, donate=(), prefetch=()):
    """Jit `fn` over row-sharded arrays; outputs get whatever sharding XLA
    propagates (scalars/small reductions come back replicated).

    `fn` is traced once and cached per shape/dtype signature by jax.jit.
    `prefetch` takes chunk handles (Vecs) whose tier-up should overlap
    this dispatch — typically the NEXT iteration's columns.
    """
    prefetch_chunks(prefetch)
    jfn = cached_jit(fn, donate_argnums=donate)
    # donated inputs are consumed by the first attempt — never retryable
    # across an epoch bump
    return _traced_dispatch("mrtask.map_reduce", jfn, arrays, fn,
                            retryable=not donate)


def map_chunks(fn, *arrays, in_specs=None, out_specs=None, check_vma=False,
               prefetch=()):
    """shard_map `fn` over the rows axis: fn runs once per shard ("node"),
    seeing only its local rows, and may use lax.psum/ppermute over "rows".

    in_specs/out_specs default to row-sharded in, replicated out. The
    jitted shard_map wrapper is cached by (fn code+closure, mesh, specs):
    shard_map returns a fresh object per call, so an uncached jit here
    re-traced on every invocation (R001). `prefetch` overlaps the next
    chunk handles' tier-up with this dispatch (see map_chunked).
    """
    prefetch_chunks(prefetch)
    c = _mesh.cloud()
    if in_specs is None:
        in_specs = tuple(P(_mesh.ROWS, *([None] * (a.ndim - 1))) for a in arrays)
    in_specs = tuple(in_specs)

    def smapped(*arrs):
        return _compat.shard_map(fn, mesh=c.mesh, in_specs=in_specs,
                                 out_specs=out_specs if out_specs is not None
                                 else P(), check_vma=check_vma)(*arrs)

    try:
        key = ("map_chunks", _fn_key(fn), c.mesh, in_specs,
               out_specs, check_vma)
        hash(key)
    except (TypeError, ValueError, _Uncacheable):
        return _traced_dispatch(   # h2o3-ok: R001,R011 unhashable specs fall back to the uncached legacy path; same map_chunks stage either way
            "mrtask.map_chunks",
            _compat.guard_collective(jax.jit(smapped)), arrays, fn)
    with _JIT_CACHE_LOCK:
        jfn = _JIT_CACHE.get(key)
        if jfn is None:
            jfn = _JIT_CACHE[key] = _compat.guard_collective(
                jax.jit(smapped))
        _JIT_CACHE.move_to_end(key)
        while len(_JIT_CACHE) > _JIT_CACHE_MAX:
            _JIT_CACHE.popitem(last=False)
    return _traced_dispatch("mrtask.map_chunks", jfn, arrays, fn)


def shard_sum(x, axis_name=_mesh.ROWS):
    """psum helper for use inside map_chunks bodies."""
    return jax.lax.psum(x, axis_name)


def host_fetch(x) -> "np.ndarray":
    """np.asarray of a possibly globally-sharded jax.Array.

    In a multi-controller runtime (deploy/multihost), fetching an array
    whose shards live on other processes' devices raises; gather it to
    every host first (the MRTask result-collection hop). Single-process
    arrays take the plain fast path."""
    import contextlib
    import numpy as np
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils
        # the allgather IS the work here (the MRTask result-collection
        # hop) — a traced request's remote fragment shows it
        ctx = _span("mrtask.host_fetch",
                    shape=[int(d) for d in getattr(x, "shape", ())]) \
            if _tracing.current() is not None else contextlib.nullcontext()
        with ctx:
            return np.asarray(
                multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def device_put_rows(host_array, ndim=None):
    """Place a host array onto the mesh row-sharded (dim 0 over "rows").

    Multi-controller (deploy/multihost SPMD replay): every host holds the
    FULL host array (requests replay identically), so each process builds
    its addressable shards from its own copy via make_array_from_callback
    — plain device_put cannot target non-addressable devices."""
    c = _mesh.cloud()
    nd = host_array.ndim if ndim is None else ndim
    sh = c.rows_sharding(nd)
    if jax.process_count() > 1:
        import numpy as _np
        arr = _np.asarray(host_array)
        return jax.make_array_from_callback(arr.shape, sh,
                                            lambda idx: arr[idx])
    return jax.device_put(host_array, sh)


def device_put_replicated(host_array):
    c = _mesh.cloud()
    if jax.process_count() > 1:
        import numpy as _np
        arr = _np.asarray(host_array)
        return jax.make_array_from_callback(arr.shape, c.replicated(),
                                            lambda idx: arr[idx])
    return jax.device_put(host_array, c.replicated())


def jit_rows(fn=None, *, static_argnums=(), donate_argnums=()):
    """Decorator: jit a function whose first args are row-sharded arrays.

    Just jax.jit — named for intent at call sites (an "MRTask definition").
    """
    if fn is None:
        return functools.partial(jit_rows, static_argnums=static_argnums,
                                 donate_argnums=donate_argnums)
    return _compat.guard_collective(
        jax.jit(fn, static_argnums=static_argnums,
                donate_argnums=donate_argnums))


def row_mask(padded_len: int, nrows: int, dtype=jnp.float32):
    """1.0 for real rows, 0.0 for padding — the ESPC-padding guard.

    Built inside jit from scalars so it fuses into consumers.
    """
    return (jnp.arange(padded_len) < nrows).astype(dtype)
