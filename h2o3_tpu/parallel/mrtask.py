"""The compute substrate: H2O's MRTask re-imagined for XLA.

Reference: water/MRTask.java:65 — serialize a task, fan it out over nodes in a
binary RPC tree (MRTask.java:690-754), fork-join down to one chunk per task,
run `map(Chunk[])`, then `reduce` partial POJOs back up two trees
(MRTask.java:850-921).

TPU-native design: there is no task serialization, no RPC tree and no explicit
reduce plumbing. A "map over chunks + tree reduce" is exactly what XLA compiles
a jitted computation over a row-sharded array into: the map runs shard-local,
and any cross-shard reduction (sum/min/max/…) lowers to an ICI collective
(all-reduce) with optimal scheduling. Two entry points:

  * map_reduce(fn, ...)  — jit `fn` over sharded inputs with replicated (small)
    outputs. The common case: XLA inserts the collectives. This is the moral
    equivalent of `new MRTask(){map;reduce}.doAll(frame)`.
  * map_chunks(fn, ...)  — `shard_map` when per-shard (per-"node") semantics
    are required: fn sees its local row block and may call lax.psum etc.
    Equivalent of MRTask with setupLocal/postLocal node-level hooks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel import mesh as _mesh


def map_reduce(fn, *arrays, donate=()):
    """Jit `fn` over row-sharded arrays; outputs get whatever sharding XLA
    propagates (scalars/small reductions come back replicated).

    `fn` is traced once and cached per shape/dtype signature by jax.jit.
    """
    jfn = jax.jit(fn, donate_argnums=donate)
    return jfn(*arrays)


def map_chunks(fn, *arrays, in_specs=None, out_specs=None, check_vma=False):
    """shard_map `fn` over the rows axis: fn runs once per shard ("node"),
    seeing only its local rows, and may use lax.psum/ppermute over "rows".

    in_specs/out_specs default to row-sharded in, replicated out.
    """
    c = _mesh.cloud()
    if in_specs is None:
        in_specs = tuple(P(_mesh.ROWS, *([None] * (a.ndim - 1))) for a in arrays)
    if out_specs is None:
        out_specs = P()
    smapped = jax.shard_map(
        fn, mesh=c.mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=check_vma)
    return jax.jit(smapped)(*arrays)


def shard_sum(x, axis_name=_mesh.ROWS):
    """psum helper for use inside map_chunks bodies."""
    return jax.lax.psum(x, axis_name)


def host_fetch(x) -> "np.ndarray":
    """np.asarray of a possibly globally-sharded jax.Array.

    In a multi-controller runtime (deploy/multihost), fetching an array
    whose shards live on other processes' devices raises; gather it to
    every host first (the MRTask result-collection hop). Single-process
    arrays take the plain fast path."""
    import numpy as np
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def device_put_rows(host_array, ndim=None):
    """Place a host array onto the mesh row-sharded (dim 0 over "rows").

    Multi-controller (deploy/multihost SPMD replay): every host holds the
    FULL host array (requests replay identically), so each process builds
    its addressable shards from its own copy via make_array_from_callback
    — plain device_put cannot target non-addressable devices."""
    c = _mesh.cloud()
    nd = host_array.ndim if ndim is None else ndim
    sh = c.rows_sharding(nd)
    if jax.process_count() > 1:
        import numpy as _np
        arr = _np.asarray(host_array)
        return jax.make_array_from_callback(arr.shape, sh,
                                            lambda idx: arr[idx])
    return jax.device_put(host_array, sh)


def device_put_replicated(host_array):
    c = _mesh.cloud()
    if jax.process_count() > 1:
        import numpy as _np
        arr = _np.asarray(host_array)
        return jax.make_array_from_callback(arr.shape, c.replicated(),
                                            lambda idx: arr[idx])
    return jax.device_put(host_array, c.replicated())


def jit_rows(fn=None, *, static_argnums=(), donate_argnums=()):
    """Decorator: jit a function whose first args are row-sharded arrays.

    Just jax.jit — named for intent at call sites (an "MRTask definition").
    """
    if fn is None:
        return functools.partial(jit_rows, static_argnums=static_argnums,
                                 donate_argnums=donate_argnums)
    return jax.jit(fn, static_argnums=static_argnums,
                   donate_argnums=donate_argnums)


def row_mask(padded_len: int, nrows: int, dtype=jnp.float32):
    """1.0 for real rows, 0.0 for padding — the ESPC-padding guard.

    Built inside jit from scalars so it fuses into consumers.
    """
    return (jnp.arange(padded_len) < nrows).astype(dtype)
