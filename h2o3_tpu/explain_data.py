"""Model explanation suite — the h2o-py `h2o.explain` / model-understanding
surface rebuilt TPU-native.

Reference: water/rapids/PermutationVarImp.java (permutation importance as
cluster MRTasks), hex/PartialDependence (h2o-core partial-dependence handler,
`h2o.partial_plot`), h2o-py explain module (model correlation heatmap,
varimp heatmap, learning curve, ICE). Plots in the reference are
client-side matplotlib over REST-served tables; here the tables ARE the
product (data frames / dicts); matplotlib stays optional.

TPU-native design: PDP and ICE batch every grid point into ONE scoring call —
the (n × G) scoring matrix is a single jitted program over the row-sharded
design matrix, not G sequential scores; permutation importance shuffles ON
DEVICE via jax.random.permutation and rescores, one program per feature."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.core.frame import Frame, Vec, T_CAT


# ---------------------------------------------------------------------------
def _score_col(model, X):
    """Margin-free scoring helper: probability of class 1 for binomial,
    prediction for regression."""
    out = model._score_matrix(X)
    if model._is_classifier and model.nclasses == 2:
        return out[:, 1]
    if model._is_classifier:
        return out  # (n, K)
    return out


def _grid_for(frame, column, nbins):
    v = frame.vec(column)
    if v.type == T_CAT:
        return np.arange(len(v.levels()), dtype=np.float32), True
    col = v.to_numpy()
    return np.linspace(np.nanmin(col), np.nanmax(col), nbins,
                       dtype=np.float32), False


def _set_feature(di, X, column, g, is_cat):
    """Overwrite one original column with value g in the design matrix —
    handles both label-mode (one slot) and onehot-mode (indicator group)."""
    if column in di.feature_names:          # label mode / numeric onehot
        # the design matrix holds standardized values for numeric columns
        # only in onehot mode with standardize=True (label-mode/tree models
        # keep raw units even though standardize defaults True) — transform
        # the raw grid value to match
        if not is_cat and getattr(di, "standardize", False) \
                and getattr(di, "cat_mode", "label") == "onehot" \
                and column in getattr(di, "means", {}):
            g = (float(g) - di.means[column]) / max(di.sigmas[column], 1e-10)
        return X.at[:, di.feature_names.index(column)].set(jnp.float32(g))
    if is_cat and column in di.cat_cols:    # onehot group
        base = 0
        for c in di.cat_cols:
            k = di.cardinalities[c]
            if c == column:
                Xg = X.at[:, base:base + k].set(0.0)
                return Xg.at[:, base + int(g)].set(1.0)
            base += k
    raise KeyError(f"column {column} not in the model's design matrix")


def partial_dependence(model, frame: Frame, column: str, nbins: int = 20,
                       targets=None):
    """PDP: mean prediction as `column` sweeps its range, all other columns
    as observed (hex PartialDependence semantics; weighted mean over rows).

    Returns dict with 'grid' and 'mean_response' (and 'stddev_response')."""
    di = model._dinfo
    X = di.matrix(frame)
    w = di.weights(frame)
    v = frame.vec(column)
    grid, is_cat = _grid_for(frame, column, nbins)
    means, sds = [], []
    wsum = float(np.asarray(jnp.sum(w)))
    for g in grid:
        Xg = _set_feature(di, X, column, g, is_cat)
        p = _score_col(model, Xg)
        if p.ndim > 1:
            p = p[:, 1] if p.shape[1] == 2 else p[:, 0]
        mu = float(np.asarray(jnp.sum(p * w))) / max(wsum, 1e-30)
        var = float(np.asarray(jnp.sum(w * (p - mu) ** 2))) / max(wsum, 1e-30)
        means.append(mu)
        sds.append(var ** 0.5)
    grid_out = list(v.levels()) if is_cat else [float(g) for g in grid]
    return {"column": column, "grid": grid_out,
            "mean_response": means, "stddev_response": sds}


def ice(model, frame: Frame, column: str, nbins: int = 20,
        row_fraction: float = 1.0):
    """Individual Conditional Expectation: per-row response curves over the
    grid (h2o-py ice_plot data). Returns (grid, curves (n_rows, G))."""
    di = model._dinfo
    X = di.matrix(frame)
    n = frame.nrows
    grid, is_cat = _grid_for(frame, column, nbins)
    curves = []
    for g in grid:
        p = _score_col(model, _set_feature(di, X, column, g, is_cat))
        if p.ndim > 1:
            p = p[:, 1] if p.shape[1] == 2 else p[:, 0]
        curves.append(np.asarray(p)[:n])
    C = np.stack(curves, axis=1)
    if row_fraction < 1.0:
        k = max(1, int(round(row_fraction * n)))
        C = C[np.linspace(0, n - 1, k).astype(int)]
    return [float(g) for g in grid], C


def permutation_varimp(model, frame: Frame, metric: str = "AUTO",
                       n_repeats: int = 1, seed: int = 42):
    """PermutationVarImp.java: drop in scoring metric when one feature is
    shuffled. Shuffle happens on device. Returns list of rows like
    variable_importances (relative = metric degradation)."""
    from h2o3_tpu.models import metrics as M
    di = model._dinfo
    X = di.matrix(frame)
    y = di.response(frame)
    w = di.weights(frame)
    w = jnp.where(jnp.isnan(y), 0.0, w)

    def score(Xv):
        out = model._score_matrix(Xv)
        if model._is_classifier and model.nclasses == 2:
            m = M.binomial_metrics(y, out[:, 1], w)
            return m.auc if metric in ("AUTO", "auc") else m.logloss
        if model._is_classifier:
            return M.multinomial_metrics(y, out, w).logloss
        m = M.regression_metrics(y, out, w)
        return m.rmse
    base = score(X)
    higher_is_better = model._is_classifier and model.nclasses == 2 and \
        metric in ("AUTO", "auc")
    key = jax.random.PRNGKey(seed)
    rows = []
    n = frame.nrows
    for j, name in enumerate(di.feature_names):
        deltas = []
        for r in range(n_repeats):
            key, k = jax.random.split(key)
            # permute only real rows; padding stays in place
            perm = jax.random.permutation(k, n)
            idx = jnp.arange(X.shape[0])
            src = jnp.where(idx < n, jnp.pad(perm, (0, X.shape[0] - n)), idx)
            Xp = X.at[:, j].set(X[src, j])
            sc = score(Xp)
            deltas.append(base - sc if higher_is_better else sc - base)
        rows.append({"variable": name,
                     "relative_importance": float(np.mean(deltas))})
    mx = max((r["relative_importance"] for r in rows), default=1.0) or 1.0
    tot = sum(max(r["relative_importance"], 0.0) for r in rows) or 1.0
    for r in rows:
        r["scaled_importance"] = r["relative_importance"] / mx
        r["percentage"] = max(r["relative_importance"], 0.0) / tot
    rows.sort(key=lambda r: -r["relative_importance"])
    return rows


def varimp_heatmap(models):
    """h2o-py varimp_heatmap data: (feature × model) scaled importances."""
    feats = []
    cols = {}
    for m in models:
        vi = m.varimp() or []
        mid = m.model_id or m.algo
        cols[mid] = {r["variable"]: r["scaled_importance"] for r in vi}
        for r in vi:
            if r["variable"] not in feats:
                feats.append(r["variable"])
    mat = np.full((len(feats), len(cols)), np.nan)
    for cj, mid in enumerate(cols):
        for fi, f in enumerate(feats):
            if f in cols[mid]:
                mat[fi, cj] = cols[mid][f]
    return feats, list(cols), mat


def model_correlation(models, frame: Frame):
    """h2o-py model_correlation_heatmap data: correlation of predictions."""
    preds = []
    names = []
    for m in models:
        p = m.predict(frame)
        arr = p.to_numpy()
        # probability of last class for classifiers, prediction otherwise
        preds.append(arr[:, -1] if arr.shape[1] > 1 else arr[:, 0])
        names.append(m.model_id or m.algo)
        from h2o3_tpu.core.kvstore import DKV
        DKV.remove(p.key)
    P = np.stack(preds, axis=1)
    return names, np.corrcoef(P, rowvar=False)


def learning_curve(model):
    """h2o-py learning_curve_plot data from the scoring history."""
    hist = model.scoring_history() or []
    if not hist:
        return {}
    xs = [h.get("number_of_trees") or h.get("iteration") or i
          for i, h in enumerate(hist)]
    series = {}
    for k in hist[-1]:
        if k.startswith("training_") or k.startswith("validation_"):
            series[k] = [h.get(k) for h in hist]
    return {"x": xs, "series": series}


def explain(model, frame: Frame, columns: int = 3):
    """h2o.explain(model, frame) analog: bundle of explanation artifacts."""
    out = {"model_id": model.model_id, "algo": model.algo}
    if model.varimp():
        out["variable_importances"] = model.varimp()
        top = [r["variable"] for r in model.varimp()[:columns]]
    else:
        top = list(model._dinfo.feature_names[:columns])
    out["partial_dependence"] = {
        c: partial_dependence(model, frame, c)
        for c in top if c in model._dinfo.predictors}
    out["learning_curve"] = learning_curve(model)
    return out
