"""sklearn-compatible estimator surface (h2o-py/h2o/sklearn/__init__.py).

The reference generates ~60 wrapper classes (one Classifier / Regressor /
Estimator triple per algo, plus AutoML and the TargetEncoder transformer)
so h2o models drop into sklearn ``Pipeline`` / ``GridSearchCV``. Same
surface here, generated over the native TPU estimators::

    from h2o3_tpu.sklearn import H2OGradientBoostingClassifier
    clf = H2OGradientBoostingClassifier(ntrees=20)
    GridSearchCV(clf, {"max_depth": [3, 5]}).fit(X, y)
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu import models as _m
from h2o3_tpu.sklearn.wrapper import (BaseH2OAdapter, H2OClassifierAdapter,
                                      H2ORegressorAdapter,
                                      H2OTransformerAdapter, _to_frame)

# (public stem, native class, supervised?) — mirrors the reference's
# gen_models table in h2o/sklearn/__init__.py
_SUPERVISED = [
    ("H2OGradientBoosting", _m.H2OGradientBoostingEstimator),
    ("H2ORandomForest", _m.H2ORandomForestEstimator),
    ("H2OGeneralizedLinear", _m.H2OGeneralizedLinearEstimator),
    ("H2ODeepLearning", _m.H2ODeepLearningEstimator),
    ("H2OXGBoost", _m.H2OXGBoostEstimator),
    ("H2ONaiveBayes", _m.H2ONaiveBayesEstimator),
    ("H2ORuleFit", _m.H2ORuleFitEstimator),
    ("H2OGeneralizedAdditive", _m.H2OGeneralizedAdditiveEstimator),
    ("H2OSupportVectorMachine", _m.H2OSupportVectorMachineEstimator),
    ("H2OStackedEnsemble", _m.H2OStackedEnsembleEstimator),
]
_UNSUPERVISED = [
    ("H2OKMeans", _m.H2OKMeansEstimator),
    ("H2OPrincipalComponentAnalysis", _m.H2OPrincipalComponentAnalysisEstimator),
    ("H2OSingularValueDecomposition", _m.H2OSingularValueDecompositionEstimator),
    ("H2OGeneralizedLowRank", _m.H2OGeneralizedLowRankEstimator),
    ("H2OIsolationForest", _m.H2OIsolationForestEstimator),
    ("H2OExtendedIsolationForest", _m.H2OExtendedIsolationForestEstimator),
    ("H2OAggregator", _m.H2OAggregatorEstimator),
]

__all__ = []


def _make(stem: str, base, native, classification):
    cls = type(stem, (base,), {
        "_h2o_class": native,
        "_classification": classification,
        "__doc__": (f"sklearn adapter over h2o3_tpu.models."
                    f"{native.__name__} (algo '{native.algo}').\n\n"
                    f"Accepts every native parameter as a keyword; see "
                    f"``{native.__name__}`` for parameter docs."),
        "__module__": __name__,
    })
    globals()[stem] = cls
    __all__.append(stem)
    return cls


for _stem, _cls in _SUPERVISED:
    _make(_stem + "Classifier", H2OClassifierAdapter, _cls, True)
    _make(_stem + "Regressor", H2ORegressorAdapter, _cls, False)
    _make(_stem + "Estimator", H2ORegressorAdapter, _cls, False)

for _stem, _cls in _UNSUPERVISED:
    _make(_stem + "Estimator", H2OTransformerAdapter, _cls, None)

# NaiveBayes / SVM only classify in the reference; their Regressor shims
# are therefore withdrawn from the public list
for _name in ("H2ONaiveBayesRegressor", "H2ONaiveBayesEstimator",
              "H2OSupportVectorMachineRegressor"):
    globals().pop(_name, None)
    __all__.remove(_name)


class H2OTargetEncoderTransformer(H2OTransformerAdapter):
    """CV-safe categorical target encoding as a sklearn transformer
    (ai/h2o/targetencoding via h2o/sklearn H2OTargetEncoderEstimator)."""
    _h2o_class = _m.H2OTargetEncoderEstimator
    _classification = False

    def fit(self, X, y=None, **kw):
        frame, names = _to_frame(X)
        self._feature_names = names
        if y is not None:
            frame["__te_y__"] = np.asarray(y, np.float64)
        est = self._h2o_class(**self._params)
        est.train(x=names, y="__te_y__", training_frame=frame)
        self.estimator_ = est
        return self

    def transform(self, X):
        frame, _ = _to_frame(X, self._feature_names)
        out = self.estimator_.transform(frame)
        cols = [c for c in out.names if c != "__te_y__"]
        return np.column_stack([out.vec(c).to_numpy() for c in cols])


__all__.append("H2OTargetEncoderTransformer")


class H2OAutoMLClassifier(H2OClassifierAdapter):
    """AutoML leader as a sklearn classifier (h2o/sklearn H2OAutoML*)."""
    _classification = True

    @classmethod
    def _known_params(cls):
        from h2o3_tpu.automl.automl import H2OAutoML
        import inspect
        sig = inspect.signature(H2OAutoML.__init__)
        return {k: p.default for k, p in sig.parameters.items()
                if k != "self" and p.default is not inspect.Parameter.empty}

    def fit(self, X, y=None, **fit_params):
        from h2o3_tpu.automl.automl import H2OAutoML
        from h2o3_tpu.core.frame import Vec
        from h2o3_tpu.sklearn.wrapper import _RESPONSE
        frame, names = _to_frame(X)
        self._feature_names = names
        y = np.asarray(y).ravel()
        if self._classification:
            self.classes_ = np.unique(y)
            frame[_RESPONSE] = Vec.from_numpy(
                np.array([str(v) for v in y], object))
        else:
            frame[_RESPONSE] = np.asarray(y, np.float64)
        aml = H2OAutoML(**self._params)
        aml.train(x=names, y=_RESPONSE, training_frame=frame, **fit_params)
        self.automl_ = aml
        self.estimator_ = aml.leader
        return self


class H2OAutoMLRegressor(H2OAutoMLClassifier, H2ORegressorAdapter):
    _classification = False


__all__ += ["H2OAutoMLClassifier", "H2OAutoMLRegressor"]
