"""scikit-learn adapter layer (h2o-py/h2o/sklearn/wrapper.py analog).

The reference wraps its REST estimators in sklearn-compatible shells so
they slot into ``Pipeline`` / ``GridSearchCV`` / ``cross_val_score``.
Here the native estimators already live in-process on the device mesh,
so the adapter is thinner: convert ndarray/DataFrame inputs to Frames,
delegate to the native builder, and decode predictions back to numpy.

Design notes vs sklearn's introspection contract:
- ``get_params``/``set_params`` are overridden (instead of relying on
  ``__init__``-signature inspection) because the wrapped parameter set
  is data-driven from each native estimator's ``_COMMON + _defaults``.
- ``clone()`` round-trips through ``type(self)(**params)``, which the
  kwargs ``__init__`` supports directly.
"""

from __future__ import annotations

import numpy as np
from sklearn.base import (BaseEstimator, ClassifierMixin, RegressorMixin,
                          TransformerMixin)

from h2o3_tpu.core.frame import Frame, Vec
from h2o3_tpu.core.kvstore import DKV

_RESPONSE = "__sklearn_y__"


def _to_frame(X, feature_names=None) -> tuple:
    """ndarray / DataFrame / Frame -> (Frame, feature column names)."""
    if isinstance(X, Frame):
        return X, list(X.names)
    try:
        import pandas as pd
        if isinstance(X, pd.DataFrame):
            cols = {str(c): X[c].to_numpy() for c in X.columns}
            f = Frame.from_dict(cols)
            return f, list(cols)
    except ImportError:
        pass
    X = np.asarray(X)
    if X.ndim == 1:
        X = X[:, None]
    names = feature_names or [f"x{i}" for i in range(X.shape[1])]
    f = Frame.from_dict({n: np.asarray(X[:, j], np.float64)
                         for j, n in enumerate(names)})
    return f, names


class BaseH2OAdapter(BaseEstimator):
    """Common fit/predict plumbing over a native h2o3_tpu estimator."""

    _h2o_class = None          # native estimator class (set per subclass)
    _classification = None     # True / False / None (follow response type)

    def __init__(self, **params):
        self._params = dict(params)

    # ---- sklearn parameter protocol -------------------------------------
    @classmethod
    def _known_params(cls):
        c = cls._h2o_class
        return dict(getattr(c, "_COMMON", {}), **getattr(c, "_defaults", {}))

    def get_params(self, deep=True):
        out = self._known_params()
        out.update(self._params)
        return out

    def set_params(self, **params):
        unknown = set(params) - set(self._known_params())
        if unknown:
            raise ValueError(
                f"{type(self).__name__}: unknown parameters {sorted(unknown)}")
        self._params.update(params)
        return self

    # ---- fitting ---------------------------------------------------------
    def fit(self, X, y=None, **fit_params):
        frame, names = _to_frame(X)
        self._feature_names = names
        yname = None
        owns_frame = not isinstance(X, Frame)
        if not owns_frame and y is not None:
            # never mutate the caller's Frame: attach the response to a
            # fresh handle over the same vecs
            frame = Frame(list(frame.names), list(frame.vecs))
            owns_frame = True
        if y is not None and self._classification is not None:
            y = np.asarray(y).ravel()
            if self._classification:
                self.classes_ = np.unique(y)
                lbl = np.array([str(v) for v in y], object)
                frame[_RESPONSE] = Vec.from_numpy(lbl)
            else:
                frame[_RESPONSE] = np.asarray(y, np.float64)
            yname = _RESPONSE
        est = self._h2o_class(**self._params)
        est.train(x=names, y=yname, training_frame=frame, **fit_params)
        self.estimator_ = est
        if owns_frame:
            DKV.remove(frame.key)
        return self

    def _predict_frame(self, X) -> Frame:
        frame, _ = _to_frame(X, getattr(self, "_feature_names", None))
        out = self.estimator_.predict(frame)
        if not isinstance(X, Frame):
            DKV.remove(frame.key)
        return out

    def predict(self, X):
        out = self._predict_frame(X)
        v = out.vec("predict") if "predict" in out.names else out.vecs[0]
        vals = v.to_numpy()
        DKV.remove(out.key)
        if getattr(self, "classes_", None) is not None and v.levels():
            lut = {str(c): c for c in self.classes_}
            dom = v.levels()
            return np.array([lut[dom[int(i)]] for i in vals])
        return vals

    def __sklearn_is_fitted__(self):
        return hasattr(self, "estimator_")


class H2OClassifierAdapter(ClassifierMixin, BaseH2OAdapter):
    _classification = True

    def predict_proba(self, X):
        out = self._predict_frame(X)
        # prob columns follow the 'predict' column, one per domain level,
        # ordered by the model's response domain
        dom = self.estimator_._output.response_domain
        cols = [c for c in out.names if c != "predict"]
        probs = np.column_stack([out.vec(c).to_numpy() for c in cols])
        DKV.remove(out.key)
        # re-order to self.classes_ order
        order = [dom.index(str(c)) for c in self.classes_]
        return probs[:, order]

    def predict_log_proba(self, X):
        return np.log(self.predict_proba(X))


class H2ORegressorAdapter(RegressorMixin, BaseH2OAdapter):
    _classification = False


class H2OTransformerAdapter(TransformerMixin, BaseH2OAdapter):
    """Unsupervised estimators exposed as sklearn transformers: KMeans
    labels via predict, PCA/SVD/GLRM projections via transform."""
    _classification = None

    def transform(self, X):
        out = self._predict_frame(X)
        M = np.column_stack([v.to_numpy() for v in out.vecs])
        DKV.remove(out.key)
        return M

    def fit_transform(self, X, y=None, **kw):
        return self.fit(X, y, **kw).transform(X)
