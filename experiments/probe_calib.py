import time, numpy as np, jax, jax.numpy as jnp
def timeit(f, *a, n=10, warm=3):
    for _ in range(warm): jax.block_until_ready(f(*a))
    t0 = time.time()
    for _ in range(n): r = f(*a)
    jax.block_until_ready(r)
    return (time.time() - t0) / n
rng = np.random.default_rng(0)
N = 2_000_000
v = jnp.asarray(rng.normal(0,1,N), jnp.float32)
big = jnp.asarray(rng.normal(0,1,(4096, 4096)), jnp.bfloat16)
add = jax.jit(lambda x: x + 1.0)
mm = jax.jit(lambda a: a @ a)
red = jax.jit(lambda x: x.sum())
print("elementwise add 2M f32 :", timeit(add, v)*1e3, "ms  (8MB r+w)")
print("sum 2M f32             :", timeit(red, v)*1e3, "ms")
t = timeit(mm, big)
print("matmul 4096^3 bf16     :", t*1e3, "ms ->", 2*4096**3/t/1e12, "TFLOP/s")
v8 = jnp.asarray(rng.normal(0,1,(8, N)), jnp.float32)
add8 = jax.jit(lambda x: x + 1.0)
print("elementwise add (8,2M) :", timeit(add8, v8)*1e3, "ms  (128MB)")
# chained 10 adds in one jit — per-dispatch overhead check
def ten(x):
    for _ in range(10): x = x + 1.0
    return x
print("10x add in one jit     :", timeit(jax.jit(ten), v)*1e3, "ms")
