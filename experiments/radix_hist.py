"""Radix-factored shallow-level histogram kernel — PERF_NOTES item 1,
scoped to the regime the analysis says it can win (UNSORTED rows, small
leaf windows).

Idea: at level windows L<=2 the dense kernel's cost floor is the 256-wide
one-hot generation (~210ms/level at 11M x 32). Factor code = hi*16+lo and
fuse leaf+hi into ONE joint key compare:

    key[r,c]  = leaf[r]*16 + hi[r,c]                  (i32, VPU)
    J[(l,hi),r] = (iota == key)                       (L*16-wide compare)
    A[(l,hi,s),r] = J ? stats[s,r] : 0                (select, L*16*S lanes)
    H[(l,hi,s),lo] = A @ onehot_lo.T                  ((L*16*S, R)@(R, 16))

VPU element-ops per (row, col): L*16 (compare) + L*16*S (select) + 16
(lo compare)  vs  dense 256 (compare) + L*S (select):
    L=1:  96 vs 260  (2.7x)     L=2: 176 vs 264  (1.5x)
    L=4: 336 vs 272  (worse)    -> use radix ONLY for L<=2, dense beyond.

Run on TPU:   python experiments/radix_hist.py            (measures)
Correctness:  python experiments/radix_hist.py --interpret (any backend)
"""

import functools
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, "/root/repo")
from h2o3_tpu.ops import hist_pallas as HP  # noqa: E402

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None

NH = 16                       # hi radix width
S = HP.S_STATS
CB = HP.COL_TILE
R = HP.BLOCK_ROWS


def _radix_kernel(codesT_ref, heap_ref, stats_ref, out_ref, *, base, L,
                  nb, interpret):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    heap = heap_ref[0, :]                               # (R,)
    leaf = heap - base
    inw = (leaf >= 0) & (leaf < L)
    leaf_c = jnp.where(inw, leaf, L)                    # dead -> key >= L*NH
    nl = nb // NH                                       # lo width
    stats = stats_ref[...]                              # (S, R)
    acc = out_ref[...]
    iota_k = lax.broadcasted_iota(jnp.int32, (L * NH, R), 0)
    iota_lo = lax.broadcasted_iota(jnp.int32, (nl, R), 0)
    parts = []
    for c in range(CB):
        code = codesT_ref[c, :]                         # (R,)
        key = leaf_c * NH + (code // nl if nl != NH else code >> 4)
        lo = code % nl
        J = (iota_k == key[None, :])                    # (L*NH, R) i1
        # A[(l,hi,s), r] = J ? stats[s] : 0
        A = jnp.where(J[:, None, :], stats[None, :, :], 0.0) \
            .reshape(L * NH * S, R).astype(jnp.bfloat16)
        ohlo = (iota_lo == lo[None, :]).astype(jnp.bfloat16)   # (nl, R)
        h = lax.dot_general(A, ohlo, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        parts.append(h)                                 # (L*NH*S, nl)
    out_ref[...] = acc + jnp.stack(parts)[None]


@functools.partial(jax.jit,
                   static_argnames=("base", "L", "nb", "interpret"))
def radix_hist(codesT, heap, stats, *, base, L, nb=256, interpret=False):
    """(L, C_pad, S, nb) histogram via the radix factorization; L <= 8."""
    c_pad, n_pad = codesT.shape
    ncb = c_pad // CB
    kernel = functools.partial(_radix_kernel, base=base, L=L, nb=nb,
                               interpret=interpret)
    out = pl.pallas_call(
        kernel,
        grid=(ncb, n_pad // R),
        in_specs=[
            pl.BlockSpec((CB, R), lambda g, j: (g, j)),
            pl.BlockSpec((1, R), lambda g, j: (0, j)),
            pl.BlockSpec((S, R), lambda g, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, CB, L * NH * S, nb // NH),
                               lambda g, j: (g, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((ncb, CB, L * NH * S, nb // NH),
                                       jnp.float32),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(codesT, heap.reshape(1, n_pad), stats)
    # (ncb, CB, L*NH*S, nl) -> (L, C_pad, S, nb)
    nl = nb // NH
    out = out.reshape(ncb, CB, L, NH, S, nl)
    return out.transpose(2, 0, 1, 4, 3, 5).reshape(L, c_pad, S, nb)


def radix_math(codes, heap, stats, *, base, L, nb):
    """Pure-jnp replica of the kernel body (the factorization math, minus
    the pallas tiling) — pallas interpret mode is impractically slow at
    kernel shapes, so correctness splits into (a) this math check and
    (b) the on-TPU parity check in measure()."""
    c_pad, n_pad = codes.shape
    nl = nb // NH
    leaf = heap - base
    inw = (leaf >= 0) & (leaf < L)
    leaf_c = jnp.where(inw, leaf, L)
    outs = []
    for c in range(c_pad):
        code = codes[c]
        key = leaf_c * NH + code // nl
        lo = code % nl
        J = jax.nn.one_hot(key, L * NH, dtype=jnp.float32)      # (n, L*NH)
        A = (J[:, :, None] * stats.T[:, None, :]) \
            .reshape(n_pad, L * NH * S)
        ohlo = jax.nn.one_hot(lo, nl, dtype=jnp.float32)
        h = A.T @ ohlo                                          # (LNHS, nl)
        outs.append(h.reshape(L, NH, S, nl).transpose(0, 2, 1, 3)
                    .reshape(L, S, nb))
    return jnp.stack(outs, axis=1)                              # (L,C,S,nb)


def check_math(L=2, nb=256):
    rng = np.random.default_rng(0)
    n, c_pad = 4096, 8
    codes = jnp.asarray(rng.integers(0, nb, (c_pad, n)), jnp.int32)
    base = L - 1
    heap = jnp.asarray(rng.integers(base, base + L + 1, n), jnp.int32)
    stats = jnp.asarray(rng.normal(0, 1, (S, n)), jnp.float32)
    got = radix_math(codes, heap, stats, base=base, L=L, nb=nb)
    want = HP.sbh_hist_xla(codes, heap, stats, base=base, L=L, n_bins=nb)
    d = float(jnp.max(jnp.abs(got - want[:L])))
    print(f"radix math L={L}: max dev {d:.5f}")
    assert d < 1e-2, d
    return d


def check(interpret=True, n_pad=2 * R, L=2, nb=256):
    rng = np.random.default_rng(0)
    c_pad = 2 * CB
    codes = jnp.asarray(rng.integers(0, nb, (c_pad, n_pad)), jnp.int32)
    base = L - 1
    heap = jnp.asarray(rng.integers(base, base + L, n_pad), jnp.int32)
    stats = jnp.asarray(rng.normal(0, 1, (S, n_pad)), jnp.float32)
    got = radix_hist(codes, heap, stats, base=base, L=L, nb=nb,
                     interpret=interpret)
    want = HP.sbh_hist_xla(codes, heap, stats, base=base, L=L, n_bins=nb)
    d = float(jnp.max(jnp.abs(got - want[:L])))
    print(f"radix L={L} max dev vs xla: {d:.4f}")
    assert d < 0.5, d          # bf16 accumulation tolerance
    return d


def measure():
    N = 11_000_000
    n_pad = -(-N // R) * R
    c_pad = 32
    rng = np.random.default_rng(0)
    codes = jnp.asarray(rng.integers(0, 255, (c_pad, n_pad)), jnp.int32)
    stats = jnp.asarray(rng.normal(0, 1, (S, n_pad)), jnp.float32)
    for L in (1, 2, 4):
        base = L - 1
        heap = jnp.asarray(rng.integers(base, base + L, n_pad), jnp.int32)
        r = radix_hist(codes, heap, stats, base=base, L=L)
        float(r[0, 0, 0, 0])
        t0 = time.time()
        for _ in range(3):
            r = radix_hist(codes, heap, stats, base=base, L=L)
        float(r[0, 0, 0, 0])
        tr = (time.time() - t0) / 3 * 1e3
        d = HP.sbh_hist_pallas(codes, heap, stats, base=base, L=L,
                               n_bins=256)
        float(d[0, 0, 0, 0])
        t0 = time.time()
        for _ in range(3):
            d = HP.sbh_hist_pallas(codes, heap, stats, base=base, L=L,
                                   n_bins=256)
        float(d[0, 0, 0, 0])
        td = (time.time() - t0) / 3 * 1e3
        print(f"L={L}: radix {tr:.0f} ms  dense {td:.0f} ms  "
              f"({td / tr:.2f}x)")


if __name__ == "__main__":
    if "--interpret" in sys.argv:        # CPU-safe factorization check
        for L in (1, 2, 4):
            check_math(L=L)
    else:                                # on-TPU parity + timings
        check(interpret=False)
        measure()
