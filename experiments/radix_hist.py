"""Radix-factored shallow-level histogram kernel — PERF_NOTES item 1,
scoped to the regime the analysis says it can win (UNSORTED rows, small
leaf windows). The production kernel now lives in
h2o3_tpu/ops/hist_pallas.py (`sbh_hist_radix`, packed code planes); this
drive is the on-chip parity + timing harness for it.

Idea: at level windows L<=2 the dense kernel's cost floor is the 256-wide
one-hot generation (~210ms/level at 11M x 32). Factor code = hi*16+lo and
fuse leaf+hi into ONE joint key compare:

    key[r,c]  = leaf[r]*16 + hi[r,c]                  (i32, VPU)
    J[(l,hi),r] = (iota == key)                       (L*16-wide compare)
    A[(l,hi,s),r] = J ? stats[s,r] : 0                (select, L*16*S lanes)
    H[(l,hi,s),lo] = A @ onehot_lo.T                  ((L*16*S, R)@(R, 16))

VPU element-ops per (row, col): L*16 (compare) + L*16*S (select) + 16
(lo compare)  vs  dense 256 (compare) + L*S (select):
    L=1:  96 vs 260  (2.7x)     L=2: 176 vs 264  (1.5x)
    L=4: 336 vs 272  (worse)    -> use radix ONLY for L<=2, dense beyond.

Run on TPU:   python experiments/radix_hist.py          (parity + timings;
              prints ONE JSON line — blocked-structured off-chip)
Correctness:  python experiments/radix_hist.py --interpret
              (the factorization math vs the XLA reference, any backend —
              promoted into tier-1 as tests/test_binned_engine.py
              test_radix_factorization_math)
"""

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")
from h2o3_tpu.ops import hist_pallas as HP  # noqa: E402

NH = HP.RADIX_NH
S = HP.S_STATS
R = HP.BLOCK_ROWS


def radix_math(codes, heap, stats, *, base, L, nb):
    """Pure-jnp replica of the kernel's factorization (minus the pallas
    tiling) — pallas interpret mode is impractically slow at kernel
    shapes, so correctness splits into (a) this math check (tier-1) and
    (b) the on-TPU parity check in measure()/ops/parity.py."""
    c_pad, n_pad = codes.shape
    nl = nb // NH
    leaf = heap - base
    inw = (leaf >= 0) & (leaf < L)
    leaf_c = jnp.where(inw, leaf, L)
    outs = []
    for c in range(c_pad):
        code = codes[c].astype(jnp.int32)
        key = leaf_c * NH + code // nl
        lo = code % nl
        J = jax.nn.one_hot(key, L * NH, dtype=jnp.float32)      # (n, L*NH)
        A = (J[:, :, None] * stats.T[:, None, :]) \
            .reshape(n_pad, L * NH * S)
        ohlo = jax.nn.one_hot(lo, nl, dtype=jnp.float32)
        h = A.T @ ohlo                                          # (LNHS, nl)
        outs.append(h.reshape(L, NH, S, nl).transpose(0, 2, 1, 3)
                    .reshape(L, S, nb))
    return jnp.stack(outs, axis=1)                              # (L,C,S,nb)


def check_math(L=2, nb=256):
    rng = np.random.default_rng(0)
    n, c_pad = 4096, 8
    codes = jnp.asarray(rng.integers(0, nb, (c_pad, n)), jnp.uint8)
    base = L - 1
    heap = jnp.asarray(rng.integers(base, base + L + 1, n), jnp.int32)
    stats = jnp.asarray(rng.normal(0, 1, (S, n)), jnp.float32)
    got = radix_math(codes, heap, stats, base=base, L=L, nb=nb)
    want = HP.sbh_hist_xla(codes, heap, stats, base=base, L=L, n_bins=nb)
    d = float(jnp.max(jnp.abs(got - want[:L])))
    print(f"radix math L={L}: max dev {d:.5f}")
    assert d < 1e-2, d
    return d


def check_chip(n_pad=2 * R, L=2, nb=256):
    """On-chip parity: the packed radix kernel vs the XLA reference."""
    rng = np.random.default_rng(0)
    c_pad = 16
    u8 = jnp.asarray(rng.integers(0, nb, (c_pad, n_pad)), jnp.uint8)
    packed = HP.pack_codes(u8)
    base = L - 1
    heap = jnp.asarray(rng.integers(base, base + L, n_pad), jnp.int32)
    stats = jnp.asarray(rng.normal(0, 1, (S, n_pad)), jnp.float32)
    got = HP.sbh_hist_radix(packed, heap, stats, base=base, L=L, n_bins=nb)
    want = HP.sbh_hist_xla(u8, heap, stats, base=base, L=L, n_bins=nb)
    d = float(jnp.max(jnp.abs(got[:L, :c_pad] - want[:L])))
    print(f"radix L={L} max dev vs xla: {d:.4f}", file=sys.stderr)
    assert d < 0.5, d          # bf16 accumulation tolerance
    return d


def measure():
    """Per-window radix vs dense timings at the honest bench shape;
    returns the rows for the JSON record."""
    N = 11_000_000
    n_pad = -(-N // R) * R
    c_pad = 32
    rng = np.random.default_rng(0)
    u8 = jnp.asarray(rng.integers(0, 255, (c_pad, n_pad)), jnp.uint8)
    packed = HP.pack_codes(u8)
    stats = jnp.asarray(rng.normal(0, 1, (S, n_pad)), jnp.float32)
    rows = []
    for L in (1, 2, 4):
        base = L - 1
        heap = jnp.asarray(rng.integers(base, base + L, n_pad), jnp.int32)

        def timed(fn):
            r = fn()
            float(r[0, 0, 0, 0])         # relay-safe sync
            t0 = time.time()
            for _ in range(3):
                r = fn()
            float(r[0, 0, 0, 0])
            return (time.time() - t0) / 3 * 1e3

        tr = timed(lambda: HP.sbh_hist_radix(
            packed, heap, stats, base=base, L=L, n_bins=256))
        td = timed(lambda: HP.sbh_hist_pallas(
            packed, heap, stats, base=base, L=L, n_bins=256))
        print(f"L={L}: radix {tr:.0f} ms  dense {td:.0f} ms  "
              f"({td / tr:.2f}x)", file=sys.stderr)
        rows.append({"window": L, "radix_ms": round(tr, 1),
                     "dense_ms": round(td, 1),
                     "speedup": round(td / tr, 2)})
    return rows


if __name__ == "__main__":
    if "--interpret" in sys.argv:        # CPU-safe factorization check
        for L in (1, 2, 4):
            check_math(L=L)
    elif not HP.use_pallas():
        # the drive's record must be structured even when the chip is
        # unreachable — name the stage, never a bare traceback
        print(json.dumps({
            "drive": "radix_hist", "blocked": True,
            "blocked_stage": "tpu-backend-unavailable",
            "backend": jax.default_backend(),
            "radix_supported": False}))
    else:                                # on-TPU parity + timings
        dev = check_chip()
        print(json.dumps({
            "drive": "radix_hist", "blocked": False,
            "backend": jax.default_backend(),
            "radix_supported": HP.radix_supported(),
            "parity_max_dev": dev,
            "windows": measure()}))
