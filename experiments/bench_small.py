"""Small-scale TPU check of the binned trainer before full bench."""
import json, time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
import sys; sys.path.insert(0, "/root/repo")
from h2o3_tpu.models.tree import binned as BN

N, C, DEPTH, NBINS = 1_000_000, 28, 8, 255
key = jax.random.PRNGKey(7)
kx, ky = jax.random.split(key)
X = jax.random.normal(kx, (N, C), jnp.float32)
logit = 1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.6 * X[:, 2] * X[:, 3]
y = (jax.random.uniform(ky, (N,)) < jax.nn.sigmoid(logit)).astype(jnp.float32)
Xs = np.asarray(X[:1 << 18])
spec = BN.make_bins(Xs, np.zeros(C, bool), NBINS)
codes = BN.quantize(X, spec)
grower = BN.BinnedGrower(spec, max_depth=DEPTH, min_rows=1.0,
                         min_split_improvement=0.0)
trainer = BN.gbm_chunk_trainer(grower, N, dist="bernoulli", eta=0.1,
                               sample_rate=1.0, mtries=0, k_trees=10)
n_pad = grower.layout(N)
y1 = BN.pad_rows(y, n_pad); w1 = BN.pad_rows(jnp.ones(N, jnp.float32), n_pad)
p0 = float(jnp.mean(y))
F = jnp.where(jnp.arange(n_pad) < N,
              float(np.log(p0 / (1 - p0))), 0.0).astype(jnp.float32)
k = jax.random.PRNGKey(0)
k, kc = jax.random.split(k)
t0 = time.time(); F, _ = trainer(codes, y1, w1, F, kc); print("warm/compile:", round(time.time()-t0,1), "s, F0:", float(F[0]))
t0 = time.time()
for _ in range(2):
    k, kc = jax.random.split(k)
    F, _ = trainer(codes, y1, w1, F, kc)
float(F[0]); dt = (time.time() - t0)
print(f"20 trees: {dt:.2f}s -> {N*20/dt/1e6:.1f}M row*trees/s")
# quality: AUC on device
p = jax.nn.sigmoid(F[:N])
order = jnp.argsort(p)
r = jnp.zeros(N).at[order].set(jnp.arange(1, N + 1, dtype=jnp.float32))
npos = float(jnp.sum(y)); nneg = N - npos
auc = (float(jnp.sum(r * y)) - npos * (npos + 1) / 2) / (npos * nneg)
print("AUC after 30 trees:", round(auc, 4))
