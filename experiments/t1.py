import sys, time, numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
sys.path.insert(0, "/root/repo")
print("devices:", jax.devices(), flush=True)
from h2o3_tpu.ops.hist_pallas import hist_pallas, hist_segsum, BLOCK_ROWS, N_STATS
rng = np.random.default_rng(0)
L, B, C_pad = 8, 256, 32
nblk = 16
n_pad = nblk * BLOCK_ROWS
codes = jnp.asarray(rng.integers(0, B, (n_pad, C_pad)), jnp.int32)
stats = jnp.asarray(rng.normal(0, 1, (N_STATS, n_pad)), jnp.float32)
bl = jnp.asarray(np.sort(rng.integers(0, L, nblk)), jnp.int32)
t0=time.time()
h_ref = hist_segsum(codes, stats, bl, n_leaves=L, n_bins=B)
h_ref_np = np.asarray(h_ref)
print("segsum done", time.time()-t0, "s", flush=True)
t0=time.time()
h_pal = hist_pallas(codes, stats, bl, n_leaves=L, n_bins=B)
h_pal_np = np.asarray(h_pal)
print("pallas done", time.time()-t0, "s", flush=True)
err = np.abs(h_ref_np - h_pal_np).max()
print("correctness max|diff|:", err, flush=True)
