"""Smoke: BinnedGrower + gbm_chunk_trainer e2e on CPU, AUC sanity."""
import sys, time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "/root/repo")

from h2o3_tpu.models.tree import binned as BN

rng = np.random.default_rng(0)
n, C = 20000, 8
X = rng.normal(0, 1, (n, C)).astype(np.float32)
logit = 1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.6 * X[:, 2] * X[:, 3]
y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
X[rng.random((n, C)) < 0.02] = np.nan  # NAs

is_cat = np.zeros(C, bool)
spec = BN.make_bins(X, is_cat, nbins=64)
codes = BN.quantize(jnp.asarray(X), spec)
print("codes", codes.shape, codes.dtype, "nb", spec.n_bins, "bval", spec.b_val)

grower = BN.BinnedGrower(spec, max_depth=5, min_rows=10,
                         min_split_improvement=1e-5)
trainer = BN.gbm_chunk_trainer(grower, n, dist="bernoulli", eta=0.1,
                               sample_rate=1.0, mtries=0, k_trees=10)

n_pad = grower.layout(n)
y1 = BN.pad_rows(jnp.asarray(y), n_pad)
w1 = BN.pad_rows(jnp.ones(n, jnp.float32), n_pad)
p0 = float(y.mean())
F = jnp.where(jnp.arange(n_pad) < n,
              np.log(p0 / (1 - p0)), 0.0).astype(jnp.float32)
key = jax.random.PRNGKey(0)
t0 = time.time()
for it in range(5):
    F, trees = trainer(codes, y1, w1, F, key)
    key, _ = jax.random.split(key)
F = np.asarray(F)[:n]
print("50 trees in", round(time.time() - t0, 1), "s")
p = 1 / (1 + np.exp(-F))

# AUC
order = np.argsort(p)
r = np.empty(n); r[order] = np.arange(1, n + 1)
npos = y.sum(); nneg = n - npos
auc = (r[y == 1].sum() - npos * (npos + 1) / 2) / (npos * nneg)
print("train AUC after 50 trees:", round(float(auc), 4))
print("auc check:", auc)
print("OK")

# --- compare with the adaptive engine on identical data ---
from h2o3_tpu.models.tree import engine as E
from h2o3_tpu.models.tree.shared_tree import _grad_hess
Xj = jnp.asarray(X)
g2 = E.TreeGrower(nbins=64, max_depth=5, min_rows=10, min_split_improvement=1e-5)
F2 = jnp.full(n, np.log(p0 / (1 - p0)), jnp.float32)
w = jnp.ones(n, jnp.float32)
k = jax.random.PRNGKey(0)
t0 = time.time()
for t in range(50):
    res, hess = _grad_hess("bernoulli", F2, jnp.asarray(y))
    col, thr, nal, val, heap, _ = g2.grow(Xj, w, res, key=k)
    val = E.gamma_pass(heap, w, res, hess, val, nodes=g2.nodes)
    F2 = F2 + 0.1 * val[heap]
F2 = np.asarray(F2)
print("adaptive 50 trees in", round(time.time() - t0, 1), "s")
p2 = 1 / (1 + np.exp(-F2))
order = np.argsort(p2); r = np.empty(n); r[order] = np.arange(1, n + 1)
auc2 = (r[y == 1].sum() - npos * (npos + 1) / 2) / (npos * nneg)
print("adaptive train AUC:", round(float(auc2), 4))
