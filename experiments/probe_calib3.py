import time, numpy as np, jax, jax.numpy as jnp

def sync(r): _ = float(jnp.asarray(r).ravel()[0].astype(jnp.float32))
def timeit(f, *a, n=3):
    for _ in range(2): r = f(*a)
    sync(r)
    t0 = time.time()
    for _ in range(n): r = f(*a)
    sync(r)
    return (time.time() - t0) / n

rng = np.random.default_rng(0)
big = jnp.asarray(rng.normal(0,1,(4096, 4096)), jnp.bfloat16)

def chain_mm(k):
    def f(a):
        x = a
        for _ in range(k):
            x = (x @ a)
        return x
    return jax.jit(f)

t1 = timeit(chain_mm(1), big)
t20 = timeit(chain_mm(20), big)
per = (t20 - t1) / 19
print(f"mm x1: {t1*1e3:.2f}ms  x20: {t20*1e3:.2f}ms  -> per-mm {per*1e3:.3f}ms = {2*4096**3/per/1e12:.0f} TFLOP/s, dispatch overhead ~{(t1-per)*1e3:.2f}ms")

v16 = jnp.asarray(rng.normal(0,1,(16, 2_000_000)), jnp.float32)
def chain_add(k):
    def f(x):
        for i in range(k): x = x + 1.0
        return x
    return jax.jit(f)
a1 = timeit(chain_add(1), v16); a20 = timeit(chain_add(20), v16)
pera = (a20 - a1) / 19
print(f"add(128MB) x1: {a1*1e3:.2f}ms x20: {a20*1e3:.2f}ms -> per-add {pera*1e3:.3f}ms = {2*128/pera/1e3:.0f} GB/s")
