"""Measure gather/scatter/cumsum at bench scale (n=11M) with in-jit loops to
amortize the ~25ms tunnel latency."""
import time, numpy as np, jax, jax.numpy as jnp
from jax import lax

N, C = 11_000_000, 28
rng = np.random.default_rng(0)
codes_T = jnp.asarray(rng.integers(0, 256, (C, N)), jnp.int32)   # (C, n)
codes_R = jnp.asarray(rng.integers(0, 256, (N, C)), jnp.int32)   # (n, C)
codes_R8 = codes_R.astype(jnp.uint8)
perm = jnp.asarray(rng.permutation(N), jnp.int32)
stats = jnp.asarray(rng.normal(0, 1, (8, N)), jnp.float32)
vals = jnp.asarray(rng.normal(0, 1, N), jnp.float32)

def sync(r): _ = float(jnp.asarray(r).ravel()[0].astype(jnp.float32))

def timek(f, *a, k=8):
    r = f(*a); sync(r)
    t0 = time.time(); r = f(*a); sync(r)
    return (time.time() - t0) / k

K = 8
@jax.jit
def gather_T(c, p):
    def body(i, acc):
        return acc + c[:, (p + i)].astype(jnp.int32).sum()
    return lax.fori_loop(0, K, body, jnp.int32(0))

@jax.jit
def gather_R(c, p):
    def body(i, acc):
        return acc + c[(p + i)].astype(jnp.int32).sum()
    return lax.fori_loop(0, K, body, jnp.int32(0))

@jax.jit
def gather_stats(s, p):
    def body(i, acc):
        return acc + s[:, (p + i)].sum()
    return lax.fori_loop(0, K, body, jnp.float32(0))

@jax.jit
def scatter_perm(v, p):
    def body(i, acc):
        out = jnp.zeros_like(v).at[(p + i) % N].set(v)
        return acc + out[0]
    return lax.fori_loop(0, K, body, jnp.float32(0))

@jax.jit
def cumsum_n(v):
    def body(i, acc):
        return acc + jnp.cumsum(v + i)[-1]
    return lax.fori_loop(0, K, body, jnp.float32(0))

@jax.jit
def transpose_RT(c):
    def body(i, acc):
        return acc + (c + i).T.astype(jnp.int32)[:, ::1024].sum()
    return lax.fori_loop(0, K, body, jnp.int32(0))

print("gather codes (C,n)[:,perm] int32:", timek(gather_T, codes_T, perm)*1e3, "ms")
print("gather codes (n,C)[perm] int32  :", timek(gather_R, codes_R, perm)*1e3, "ms")
print("gather codes (n,C)[perm] uint8  :", timek(gather_R, codes_R8, perm)*1e3, "ms")
print("gather stats (8,n)[:,perm] f32  :", timek(gather_stats, stats, perm)*1e3, "ms")
print("scatter (n,) f32 perm           :", timek(scatter_perm, vals, perm)*1e3, "ms")
print("cumsum (n,) f32                 :", timek(cumsum_n, vals)*1e3, "ms")
print("transpose (n,C)->(C,n) int32    :", timek(transpose_RT, codes_R)*1e3, "ms")
