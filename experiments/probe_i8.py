"""Does the int8 Pallas dot compile + run, and how fast vs bf16?"""
import time, numpy as np, jax, jax.numpy as jnp, sys
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
sys.path.insert(0, "/root/repo")
from h2o3_tpu.ops import hist_pallas as HP

N = 11_000_000
R = HP.BLOCK_ROWS
n_pad = -(-(N + 1) // R) * R
C_pad, BP = 32, 256
rng = np.random.default_rng(0)
codesU8 = jnp.asarray(rng.integers(0, 255, (C_pad, n_pad)), jnp.uint8)
codesT = HP.pack_codes(codesU8)      # packed i32 code plane (round 4)
stats = jnp.asarray(rng.normal(0, 1, (4, n_pad)), jnp.float32)
stats_i8 = jnp.asarray(rng.integers(-127, 128, (4, n_pad)), jnp.int32)

def bench(name, fn, *args, n=3):
    r = fn(*args)
    print(name, "first:", float(jnp.asarray(r).ravel()[0].astype(jnp.float32)))
    t0 = time.time()
    for _ in range(n):
        r = fn(*args)
    float(jnp.asarray(r).ravel()[0].astype(jnp.float32))
    print(f"  {name}: {(time.time()-t0)/n*1e3:.1f} ms")

for d, L in ((3, 8), (7, 128)):
    base = L - 1
    heap = jnp.asarray(rng.integers(base, base + L, n_pad), jnp.int32)
    bench(f"i8 hist L={L}",
          lambda c, h, st, base=base, L=L: HP.sbh_hist_pallas_i8(
              c, h, st, base=base, L=L, n_bins=BP).sum(),
          codesT, heap, stats_i8)
    bench(f"bf16 hist L={L}",
          lambda c, h, st, base=base, L=L: HP.sbh_hist_pallas(
              c, h, st, base=base, L=L, n_bins=BP).sum(),
          codesT, heap, stats)

# correctness: i8 vs exact numpy on small
n0 = 4 * R
c0u = jnp.asarray(rng.integers(0, BP, (C_pad, n0)), jnp.uint8)
c0 = HP.pack_codes(c0u)
h0 = jnp.asarray(rng.integers(7, 15, n0), jnp.int32)
s0 = jnp.asarray(rng.integers(-127, 128, (4, n0)), jnp.int32)
out = np.asarray(HP.sbh_hist_pallas_i8(c0, h0, s0, base=7, L=8, n_bins=BP))
ref = np.zeros((8, C_pad, 4, BP), np.int64)
cn, hn, sn = np.asarray(c0u).astype(np.int64), np.asarray(h0), np.asarray(s0)
for c in range(C_pad):
    for st in range(4):
        np.add.at(ref[:, c, st, :], (hn - 7, cn[c]), sn[st])
err = np.abs(out[:8] - ref).max()
print("i8 exactness:", err)
