"""Profile sbh_route / sbh_hist / find_splits at 11M rows on TPU."""
import time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
import sys; sys.path.insert(0, "/root/repo")
from h2o3_tpu.ops import hist_pallas as HP
from h2o3_tpu.models.tree import binned as BN

N = 11_000_000
R = HP.BLOCK_ROWS
n_pad = -(-(N + 1) // R) * R
C_pad, BP = 32, 256
rng = np.random.default_rng(0)
codesU8 = jnp.asarray(rng.integers(0, 255, (C_pad, n_pad)), jnp.uint8)
codesT = HP.pack_codes(codesU8)      # packed i32 code plane (round 4)
stats = jnp.asarray(rng.normal(0, 1, (4, n_pad)), jnp.float32)
F = jnp.zeros(n_pad, jnp.float32)


def bench(name, fn, *args, n=3):
    r = fn(*args)
    float(jnp.asarray(r[0] if isinstance(r, tuple) else r)
          .ravel()[0].astype(jnp.float32))
    t0 = time.time()
    for _ in range(n):
        r = fn(*args)
    float(jnp.asarray(r[0] if isinstance(r, tuple) else r)
          .ravel()[0].astype(jnp.float32))
    print(f"  {name}: {(time.time()-t0)/n*1e3:.1f} ms")


for d in (3, 7):
    L = 2 ** d
    base = L - 1
    heap = jnp.asarray(rng.integers(base, base + L, n_pad), jnp.int32)
    Lp = max(8, L)
    tbl = jnp.zeros((8, Lp), jnp.float32)
    route_f = jnp.zeros((Lp, BP), jnp.float32)
    valtab = jnp.zeros((8, 640), jnp.float32)
    bench(f"sbh_route L={L}",
          lambda c, h, t, r, v, f: HP.sbh_route(
              c, h, t, r, v, f, base=base, L=L),
          codesT, heap, tbl, route_f, valtab, F)
    bench(f"sbh_route L={L} emit_f",
          lambda c, h, t, r, v, f: HP.sbh_route(
              c, h, t, r, v, f, base=base, L=L, eta=0.1, emit_f=True),
          codesT, heap, tbl, route_f, valtab, F)
    bench(f"sbh_hist L={L}",
          lambda c, h, s: HP.sbh_hist(c, h, s, base=base, L=L, n_bins=BP),
          codesT, heap, stats)

# find_splits at L=128
hist = jnp.asarray(rng.random((128, C_pad, 4, BP)), jnp.float32)
is_cat = jnp.zeros(C_pad, bool)
mono = jnp.zeros(C_pad, jnp.int32)
cmask = jnp.ones((128, C_pad), bool)
lo = jnp.full(128, -3e38); hi = jnp.full(128, 3e38)
bench("find_splits L=128 (no cat)",
      lambda h: BN.find_splits_binned(
          h, is_cat, mono, cmask, lo, hi, b_val=255, min_rows=1.0,
          msi=0.0, lam=0.0, use_hess=False, l_max=128, any_cat=False)["gain"],
      hist)
bench("find_splits L=128 (cat path)",
      lambda h: BN.find_splits_binned(
          h, is_cat, mono, cmask, lo, hi, b_val=255, min_rows=1.0,
          msi=0.0, lam=0.0, use_hess=False, l_max=128, any_cat=True)["gain"],
      hist)

# fast-path route (no cat)
for d in (3, 7):
    L = 2 ** d; base = L - 1
    heap = jnp.asarray(rng.integers(base, base + L, n_pad), jnp.int32)
    Lp = max(8, L)
    tbl = jnp.zeros((8, Lp), jnp.float32)
    route_f = jnp.zeros((Lp, BP), jnp.float32)
    valtab = jnp.zeros((8, 640), jnp.float32)
    bench(f"sbh_route L={L} FAST",
          lambda c, h, t, r, v, f, base=base, L=L: HP.sbh_route(
              c, h, t, r, v, f, base=base, L=L, any_cat=False),
          codesT, heap, tbl, route_f, valtab, F)
    bench(f"sbh_route L={L} FAST emit_f",
          lambda c, h, t, r, v, f, base=base, L=L: HP.sbh_route(
              c, h, t, r, v, f, base=base, L=L, eta=0.1, emit_f=True,
              any_cat=False),
          codesT, heap, tbl, route_f, valtab, F)
    bench(f"sbh_hist L={L} v4",
          lambda c, h, s, base=base, L=L: HP.sbh_hist(
              c, h, s, base=base, L=L, n_bins=BP),
          codesT, heap, stats)
