"""Correctness + speed of the pallas histogram kernel vs segsum reference."""
import sys, time, numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
sys.path.insert(0, "/root/repo")
from h2o3_tpu.ops.hist_pallas import (hist_pallas, hist_segsum, BLOCK_ROWS,
                                      N_STATS)

def sync(r): _ = float(jnp.asarray(r).ravel()[0].astype(jnp.float32))

# ---- small correctness ----
rng = np.random.default_rng(0)
L, B, C_pad = 8, 256, 32
nblk = 16
n_pad = nblk * BLOCK_ROWS
codes = jnp.asarray(rng.integers(0, B, (n_pad, C_pad)), jnp.int32)
stats = jnp.asarray(rng.normal(0, 1, (N_STATS, n_pad)), jnp.float32)
bl = jnp.asarray(np.sort(rng.integers(0, L, nblk)), jnp.int32)
h_ref = hist_segsum(codes, stats, bl, n_leaves=L, n_bins=B)
h_pal = hist_pallas(codes, stats, bl, n_leaves=L, n_bins=B)
err = float(jnp.abs(h_ref - h_pal).max())
print("correctness max|diff|:", err, flush=True)
assert err < 1e-2, err

# ---- speed at bench scale ----
N = 11_000_000
L, C_pad = 256, 32
nblk = (N + BLOCK_ROWS - 1) // BLOCK_ROWS + L
n_pad = nblk * BLOCK_ROWS
codes = jnp.asarray(rng.integers(0, B, (n_pad, C_pad)), jnp.int32)
stats = jnp.asarray(rng.normal(0, 1, (N_STATS, n_pad)), jnp.float32)
bl_np = np.minimum(np.arange(nblk) * L // nblk, L - 1)
bl = jnp.asarray(bl_np, jnp.int32)

from jax import lax
@jax.jit
def run4(codes, stats, bl):
    def body(i, acc):
        h = hist_pallas(codes, stats + 0.0 * i, bl, n_leaves=L, n_bins=B)
        return acc + h[0, 0, 0, 0]
    return lax.fori_loop(0, 4, body, jnp.float32(0))

t0 = time.time(); sync(run4(codes, stats, bl)); print("compile+1st:", time.time()-t0, "s", flush=True)
t0 = time.time(); sync(run4(codes, stats, bl)); per = (time.time()-t0)/4
print(f"hist_pallas 11M x 28(32)cols x 256bins: {per*1e3:.1f} ms/level", flush=True)
print(f"-> projected tree (8 levels): {per*8*1e3:.0f} ms; 100 trees: {per*800:.1f} s", flush=True)
