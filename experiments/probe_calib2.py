import time, numpy as np, jax, jax.numpy as jnp

def timeit(f, *a, n=10, warm=3):
    """Sync via scalar readback (block_until_ready is a no-op via axon)."""
    for _ in range(warm): r = f(*a)
    _ = float(jnp.asarray(r).ravel()[0].astype(jnp.float32))
    t0 = time.time()
    for _ in range(n): r = f(*a)
    _ = float(jnp.asarray(r).ravel()[0].astype(jnp.float32))
    return (time.time() - t0) / n

rng = np.random.default_rng(0)
N = 2_000_000
v = jnp.asarray(rng.normal(0,1,N), jnp.float32)
big = jnp.asarray(rng.normal(0,1,(4096, 4096)), jnp.bfloat16)
print("elementwise add 2M f32 :", timeit(jax.jit(lambda x: x + 1.0), v)*1e3, "ms (8MB)")
t = timeit(jax.jit(lambda a: a @ a), big)
print("matmul 4096^3 bf16     :", t*1e3, "ms ->", 2*4096**3/t/1e12, "TFLOP/s")
v8 = jnp.asarray(rng.normal(0,1,(16, N)), jnp.float32)
print("elementwise add (16,2M):", timeit(jax.jit(lambda x: x + 1.0), v8)*1e3, "ms (256MB rw)")
codes = jnp.asarray(rng.integers(0, 256, (N, 28)), jnp.uint8)
perm = jnp.asarray(rng.permutation(N), jnp.int32)
vals = jnp.asarray(rng.normal(0,1,N), jnp.float32)
print("gather codes (N,28)[perm]:", timeit(jax.jit(lambda c,p: c[p]), codes, perm)*1e3, "ms")
print("scatter perm (N,) f32    :", timeit(jax.jit(lambda v,p: jnp.zeros_like(v).at[p].set(v)), vals, perm)*1e3, "ms")
print("sort_key_val (N,)        :", timeit(jax.jit(lambda k,v: jax.lax.sort_key_val(k,v)), perm, perm)*1e3, "ms")
print("cumsum f32 (N,)          :", timeit(jax.jit(lambda v: jnp.cumsum(v)), vals)*1e3, "ms")
print("segment_sum 256 (N,)     :", timeit(jax.jit(lambda v,l: jax.ops.segment_sum(v, l, num_segments=256)), vals, perm % 256)*1e3, "ms")
