"""Probe: does Pallas/Mosaic compile through the axon TPU tunnel, and what do
the primitive ops of a pre-binned histogram engine cost at bench scale?"""
import time, numpy as np, jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

print("backend:", jax.default_backend(), jax.devices())

def timeit(f, *a, n=5, warm=2):
    for _ in range(warm):
        jax.block_until_ready(f(*a))
    t0 = time.time()
    for _ in range(n):
        r = f(*a)
    jax.block_until_ready(r)
    return (time.time() - t0) / n

# trivial pallas kernel
def k(x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2.0
x = jnp.ones((256, 256), jnp.float32)
y = pl.pallas_call(k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)
print("pallas trivial OK:", float(y.sum()))

N, C = 2_000_000, 28
rng = np.random.default_rng(0)
codes = jnp.asarray(rng.integers(0, 256, (N, C)), jnp.uint8)
codes_T = jnp.asarray(np.asarray(codes).T)          # (C, N)
stats8 = jnp.asarray(rng.normal(0, 1, (8, N)), jnp.float32)
perm = jnp.asarray(rng.permutation(N), jnp.int32)
vals = jnp.asarray(rng.normal(0, 1, N), jnp.float32)

g1 = jax.jit(lambda c, p: c[p])                     # gather rows (N,C) uint8
g2 = jax.jit(lambda c, p: c[:, p])                  # gather cols of (C,N)
sc = jax.jit(lambda v, p: jnp.zeros_like(v).at[p].set(v))   # perm scatter
srt = jax.jit(lambda k, v: jax.lax.sort_key_val(k, v))
print("gather codes (N,C)[perm]  :", timeit(g1, codes, perm)*1e3, "ms")
print("gather codes (C,N)[:,perm]:", timeit(g2, codes_T, perm)*1e3, "ms")
print("scatter perm (N,) f32     :", timeit(sc, vals, perm)*1e3, "ms")
print("sort_key_val int32 (N,)   :", timeit(srt, perm, perm)*1e3, "ms")
cs = jax.jit(lambda v: jnp.cumsum(v))
print("cumsum f32 (N,)           :", timeit(cs, vals)*1e3, "ms")
