import time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

N = 176_000_000  # 704MB f32
x = jnp.ones((N,), jnp.float32)

@jax.jit
def f(x):
    return (x * 1.000001 + 1e-9).sum()

float(f(x))
for reps in (10,):
    t0 = time.time()
    s = 0.0
    for _ in range(reps):
        s = f(x)
    float(s)
    dt = (time.time() - t0) / reps
    print(f"read 704MB + reduce: {dt*1e3:.1f} ms -> {N*4/dt/1e9:.0f} GB/s")

# write test: y = x*2 (read+write 1.4GB)
@jax.jit
def g(x):
    return x * 2.0

y = g(x); float(y[0])
t0 = time.time()
for _ in range(10):
    y = g(y)
float(y[0])
dt = (time.time() - t0) / 10
print(f"read+write 704MB each: {dt*1e3:.1f} ms -> {2*N*4/dt/1e9:.0f} GB/s")
