import sys, time, numpy as np, jax, jax.numpy as jnp
from jax import lax
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

N, C = 11_000_000, 28
rng = np.random.default_rng(0)
perm = jnp.asarray(rng.permutation(N), jnp.int32)
vals = jnp.asarray(rng.normal(0, 1, N), jnp.float32)
def sync(r): _ = float(jnp.asarray(r).ravel()[0].astype(jnp.float32))
def timek(f, *a, k=4):
    t0=time.time(); r = f(*a); sync(r); print("  (compile+1st:", time.time()-t0, "s)", flush=True)
    t0 = time.time(); r = f(*a); sync(r)
    return (time.time() - t0) / k
K = 4
which = sys.argv[1]
if which == "gatherR":
    codes_R = jnp.asarray(rng.integers(0, 256, (N, C)), jnp.int32)
    @jax.jit
    def f(c, p):
        def body(i, acc): return acc + c[(p + i)].astype(jnp.int32).sum()
        return lax.fori_loop(0, K, body, jnp.int32(0))
    print("gather (n,C)[perm] int32:", timek(f, codes_R, perm)*1e3, "ms", flush=True)
elif which == "gatherT":
    codes_T = jnp.asarray(rng.integers(0, 256, (C, N)), jnp.int32)
    @jax.jit
    def f(c, p):
        def body(i, acc): return acc + c[:, (p + i)].astype(jnp.int32).sum()
        return lax.fori_loop(0, K, body, jnp.int32(0))
    print("gather (C,n)[:,perm] int32:", timek(f, codes_T, perm)*1e3, "ms", flush=True)
elif which == "scatter":
    @jax.jit
    def f(v, p):
        def body(i, acc):
            out = jnp.zeros_like(v).at[(p + i) % N].set(v)
            return acc + out[0]
        return lax.fori_loop(0, K, body, jnp.float32(0))
    print("scatter (n,) f32:", timek(f, vals, perm)*1e3, "ms", flush=True)
elif which == "cumsum":
    @jax.jit
    def f(v):
        def body(i, acc): return acc + jnp.cumsum(v + i)[-1]
        return lax.fori_loop(0, K, body, jnp.float32(0))
    print("cumsum (n,) f32:", timek(f, vals)*1e3, "ms", flush=True)
elif which == "sort":
    @jax.jit
    def f(v, p):
        def body(i, carry):
            k2, v2 = lax.sort_key_val(p + i, carry)
            return v2
        return lax.fori_loop(0, K, body, vals)
    print("sort_key_val (n,):", timek(f, vals, perm)*1e3, "ms", flush=True)
