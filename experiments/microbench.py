"""Microbenchmarks on the real chip to pick the histogram engine design.

Axon-relay rules learned the hard way: block_until_ready doesn't wait, and
any multi-MB device->host transfer costs ~100s of ms through the tunnel. So
every timed fn must END in a scalar (device-side reduction), and we sync via
float(scalar).
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def timeit(name, fn, *args, n=3, work=1):
    float(fn(*args))  # compile + warm
    t0 = time.time()
    for _ in range(n):
        s = fn(*args)
    s = float(s)
    dt = (time.time() - t0) / n
    print(f"{name}: {dt*1e3:.2f} ms   [{s:.3g}]")
    return dt


N = 11_000_000
rng = np.random.default_rng(0)

# 0. relay round-trip latency for a trivial scalar
z = jnp.float32(1.0)
timeit("scalar round-trip", jax.jit(lambda z: z + 1), z, n=10)

# 1. matmul peak bf16: chain of 40 4k matmuls inside one jit
M = 4096
a = jnp.asarray(rng.normal(size=(M, M)), jnp.bfloat16)

@jax.jit
def mm(a):
    x = a
    for _ in range(40):
        x = jnp.dot(x, a * 1e-3, preferred_element_type=jnp.bfloat16)
    return x.astype(jnp.float32).sum()

dt = timeit("40x 4k bf16 matmul", mm, a)
print(f"  -> {40*2*M**3/dt/1e12:.1f} TFLOP/s")

# 2. HBM stream
x = jnp.asarray(rng.normal(size=(N * 4,)), jnp.float32)

@jax.jit
def ew(x):
    return (x * 1.0001 + 1.0).sum()

dt = timeit("stream 176MB f32 read", ew, x)
print(f"  -> {x.size*4/dt/1e9:.0f} GB/s read")

# 3. gather rows: (N, 4) f32 by random perm
tbl = jnp.asarray(rng.normal(size=(N, 4)), jnp.float32)
perm = jnp.asarray(rng.permutation(N).astype(np.int32))

@jax.jit
def gather_rows(tbl, perm):
    return jnp.take(tbl, perm, axis=0).sum()

dt = timeit("gather 11M rows of 16B (random)", gather_rows, tbl, perm)
print(f"  -> {N/dt/1e6:.0f} M rows/s")

col = tbl[:, 0]

@jax.jit
def gather_elem(col, perm):
    return jnp.take(col, perm).sum()

dt = timeit("gather 11M f32 scalars (random)", gather_elem, col, perm)
print(f"  -> {N/dt/1e6:.0f} M elems/s")

# 3c. sorted-ish gather (locality): perm = identity + small noise
perm_loc = jnp.asarray(
    np.clip(np.arange(N) + rng.integers(-32, 32, N), 0, N - 1).astype(np.int32))
dt = timeit("gather 11M f32 scalars (local +-32)", gather_elem, col, perm_loc)
print(f"  -> {N/dt/1e6:.0f} M elems/s")

# 4. segment_sum histogram-shaped
L, nb = 64, 256
leaf = jnp.asarray(rng.integers(0, L, N).astype(np.int32))
codes = jnp.asarray(rng.integers(0, nb, (N, 8)).astype(np.int8))
stats = jnp.asarray(rng.normal(size=(N, 2)), jnp.float32)

@jax.jit
def seghist(leaf, codes, stats):
    def one_col(c):
        idx = leaf * nb + codes[:, c].astype(jnp.int32)
        return jax.ops.segment_sum(stats, idx, num_segments=L * nb)
    return jax.lax.map(one_col, jnp.arange(8)).sum()

dt = timeit("segment_sum hist 8 cols L=64 nb=256", seghist, leaf, codes, stats)
print(f"  -> {8*N/dt/1e6:.0f} M updates/s")

# 4b. segment_sum with SORTED ids (contiguous segments)
leaf_sorted = jnp.sort(leaf)

@jax.jit
def segsorted(leaf_sorted, stats):
    return jax.ops.segment_sum(stats, leaf_sorted, num_segments=L,
                               indices_are_sorted=True).sum()

dt = timeit("segment_sum 11M->64 sorted ids", segsorted, leaf_sorted, stats)
print(f"  -> {N/dt/1e6:.0f} M updates/s")

# 5. cumsum + argsort, scalar-ended
@jax.jit
def csum(col):
    return jnp.cumsum(col).sum()

dt = timeit("cumsum 11M f32", csum, col)
keys = jnp.asarray(rng.integers(0, 1 << 30, N).astype(np.int32))

@jax.jit
def asort(keys):
    return jnp.argsort(keys).sum()

dt = timeit("argsort 11M int32", asort, keys)

@jax.jit
def ssort(keys):
    return jnp.sort(keys).sum()

dt = timeit("sort 11M int32", ssort, keys)

# 7. one-hot matmul histogram cost model: scan over 512 tiles,
#    per tile (CBnb=2048, TR=1024) @ (TR, 128)
TR, CB = 1024, 8
NT = 512
codes8 = jnp.asarray(rng.integers(0, nb, (NT * TR, CB)).astype(np.int8))
stats2 = jnp.asarray(rng.normal(size=(NT * TR, 2)), jnp.float32)
leaf2 = jnp.asarray(rng.integers(0, 64, NT * TR).astype(np.int32))

@jax.jit
def onehot_mm(codes8, stats2, leaf2):
    def tile(carry, t):
        cb = jax.lax.dynamic_slice(codes8, (t * TR, 0), (TR, CB))
        st = jax.lax.dynamic_slice(stats2, (t * TR, 0), (TR, 2))
        lf = jax.lax.dynamic_slice(leaf2, (t * TR,), (TR,))
        oh = (cb.astype(jnp.int32)[:, :, None] ==
              jnp.arange(nb, dtype=jnp.int32)[None, None, :])
        oh = oh.reshape(TR, CB * nb).astype(jnp.bfloat16)
        R = (jax.nn.one_hot(lf % 64, 64, dtype=jnp.bfloat16)[:, :, None]
             * st[:, None, :].astype(jnp.bfloat16)).reshape(TR, 128)
        h = jax.lax.dot_general(oh, R, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return carry + h, t

    init = jnp.zeros((CB * nb, 128), jnp.float32)
    out, _ = jax.lax.scan(tile, init, jnp.arange(NT))
    return out.sum()

dt = timeit("onehot-mm 512 tiles (524k rows, 8cols, nb=256, N=128)",
            onehot_mm, codes8, stats2, leaf2)
rows = NT * TR
persec = rows * CB / dt
print(f"  -> {persec/1e6:.1f} M row·cols/s -> level(11M,28c) = "
      f"{N*28/persec*1e3:.0f} ms")

# 8. code-sorted segment-matmul cost model: per column, gather stats panel by
#    static perm, then tile-matmul leaf-onehot(64)xstats over code blocks.
#    Cost ~ gather(11M) + matmul (TR,128)x... per tile: (128, TR) @ (TR, 128)
panel = jnp.concatenate([stats2, jnp.zeros((NT * TR, 2), jnp.float32)], 1)

@jax.jit
def sorted_segmm(panel, perm_, leaf2):
    g = jnp.take(panel, perm_, axis=0)            # the per-column gather
    lf = jnp.take(leaf2, perm_)

    def tile(carry, t):
        st = jax.lax.dynamic_slice(g, (t * TR, 0), (TR, 4))
        lfT = jax.lax.dynamic_slice(lf, (t * TR,), (TR,))
        ohl = jax.nn.one_hot(lfT % 64, 64, dtype=jnp.bfloat16)  # (TR, 64)
        h = jax.lax.dot_general(ohl, st.astype(jnp.bfloat16),
                                (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return carry + h, t

    out, _ = jax.lax.scan(tile, jnp.zeros((64, 4), jnp.float32),
                          jnp.arange(NT))
    return out.sum()

perm2 = jnp.asarray(rng.permutation(NT * TR).astype(np.int32))
dt = timeit("code-sorted segmm 524k rows 1 col (gather+mm)",
            sorted_segmm, panel, perm2, leaf2)
print(f"  -> per col: {dt*1e3:.1f} ms for 524k rows -> "
      f"level(11M,28c) = {dt*N/ (NT*TR) * 28 * 1e3:.0f} ms")
